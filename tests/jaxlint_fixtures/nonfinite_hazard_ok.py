"""nonfinite-hazard near-miss fixture: each hazard class written with
the sanctioned guard idiom — must stay completely clean.

Parsed (never imported) by tests/test_jaxlint.py.
"""

import jax.numpy as jnp

_EPS = 1e-6


def floored_log(x):
    return jnp.log(x + _EPS)


def maximum_floored_log(x):
    return jnp.log(jnp.maximum(x, _EPS))


def producer_guarded_sqrt(x):
    var = jnp.var(x)
    return jnp.sqrt(var)


def clipped_squashed_log_prob(action):
    clipped = jnp.clip(action, -1.0 + 1e-6, 1.0 - 1e-6)
    pre_tanh = jnp.arctanh(clipped)
    return -0.5 * pre_tanh * pre_tanh


def capped_ratio(log_prob, old_log_prob, adv):
    ratio = jnp.exp(jnp.minimum(log_prob - old_log_prob, 20.0))
    return ratio * adv


def eps_scale_seed(shape):
    # the quantize.init_stats idiom: seeded AT the _EPS floor
    scale = jnp.full(shape, _EPS)
    return {"mean": jnp.zeros(shape), "scale": scale}


def floored_normalize(x):
    total = jnp.sum(x)
    return x / jnp.maximum(total, _EPS)


def conditionally_guarded_rate(x):
    total = jnp.sum(x)
    # the host-side ternary guard idiom
    return x / total if total > 0 else x
