"""jaxlint fixture (near miss, must NOT flag): branches on static
metadata (.shape), trace-time presence checks (`is None`), and static
arguments — the sanctioned idioms. Parsed only — never imported."""

import jax


@jax.jit
def head(x, n=None):
    if n is None:  # trace-time presence check on an optional arg
        n = x.shape[0]
    if x.shape[0] > 1:  # static shape metadata
        return x[:1]
    return x


def make_step(cfg):
    def step(state, flat):
        if flat.shape[0] % 4 != 0:  # shape-specialization guard
            raise ValueError("bad batch")
        return state

    return jax.jit(step, static_argnums=())
