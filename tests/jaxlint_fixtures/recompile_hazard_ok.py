"""jaxlint fixture (near miss, must NOT flag): the jit is hoisted out
of the loop and the data-dependent scalar is pinned dynamic with
jnp.asarray. Parsed only — never imported."""

import jax
import jax.numpy as jnp

step = jax.jit(lambda a: a + 1)


def per_item(xs):
    return [step(x) for x in xs]  # one callable, dispatch cache reused


tail_update = jax.jit(lambda a, n: a * 1.0)


def dispatch_tail(batch):
    n = len(batch)
    return tail_update(jnp.asarray(batch), jnp.asarray(n, jnp.int32))
