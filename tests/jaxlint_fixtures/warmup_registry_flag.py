"""jaxlint fixture (MUST FLAG warmup-registry when its key is not
registered): a jax.jit entry point with no AOT warmup registration.
The test injects the registry; parsed only — never imported."""

import jax


def make_step(cfg):
    @jax.jit
    def step(state):
        return state

    return step
