"""Flag fixture (MUST FLAG rank-affinity): shared artifact paths
written from per-rank scopes with no process identity in the path —
every host of the fleet clobbers the same file. Parsed only — never
imported."""

import json
import os


class TelemetrySession:  # stand-in sink shape; never imported
    def __init__(self, directory, **kwargs):
        self.directory = directory


def start_fleet_telemetry(base_dir, rank):
    # Same directory on every host: N hosts interleave one spans.jsonl.
    return TelemetrySession(base_dir)


def log_fleet_row(out_dir, rank, row):
    path = os.path.join(out_dir, "metrics.jsonl")  # rank never reaches it
    with open(path, "w") as f:
        json.dump(row, f)
