"""Near miss: the mailbox_protocol_flag.py shapes made safe — a
pid-unique same-directory tmp, write -> fsync -> rename, torn-read
tolerance covering the real npz exception set, and per-peer version
clocks. Parsed only — never imported."""

import os
import zipfile

import numpy as np


def snapshot_file(mailbox_dir, who):
    return os.path.join(mailbox_dir, f"host{who}", "params.npz")


def publish_atomic(mailbox_dir, who, payload):
    path = snapshot_file(mailbox_dir, who)
    tmp = f"{path}.tmp.{os.getpid()}"  # process-unique, same directory
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())  # data durable BEFORE the rename is
    os.replace(tmp, path)
    return path


def consume_tolerant(mailbox_dir, who):
    path = snapshot_file(mailbox_dir, who)
    try:
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    except (OSError, ValueError, EOFError, zipfile.BadZipFile):
        return None  # torn reads retry next poll, never fatal


def consume_per_peer_clock(mailbox_dir, schedule):
    seen = {}  # newest version PER RANK: a slow peer's news still lands
    out = []
    for peer in schedule:
        snap = consume_tolerant(mailbox_dir, peer)
        if snap is None:
            continue
        version = int(snap["version"])
        if version > seen.get(peer, -1):
            seen[peer] = version
            out.append((peer, version))
    return out
