"""jaxlint fixture (near miss, must NOT flag): the same recycled shape
WITH donation, and the alias re-derived from the donating call's
result. Parsed only — never imported."""

import jax


def make_update_step(cfg):
    def update(state, block):
        return state

    return jax.jit(update, donate_argnums=0)


def learner_loop(cfg, state, blocks):
    update = make_update_step(cfg)
    for block in blocks:
        state = update(state, block)  # donated AND rebound: in-place
    return state


def fresh_view(step_fn, state, block):
    step = jax.jit(step_fn, donate_argnums=0)
    state = step(state, block)
    quant = state["quant"]  # derived from the NEW binding
    return state, quant


def read_before_donation(step_fn, state, block):
    step = jax.jit(step_fn, donate_argnums=0)
    quant = state["quant"]
    digest = sum_host(quant)  # alias consumed BEFORE the donation ...
    state = step(state, block)
    return state, digest  # ... only the host digest survives


def sum_host(tree):
    return tree
