"""jaxlint fixture (MUST FLAG prng-reuse): one key binding consumed by
two jax.random calls, and a key consumed inside a loop that never
splits. Parsed only — never imported."""

import jax


def sample_pair(seed):
    key = jax.random.key(seed)
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))  # same binding consumed again
    return a + b


def noisy_rollout(key, steps):
    out = []
    for _ in range(steps):
        out.append(jax.random.normal(key, ()))  # same key every iteration
    return out
