"""Near miss: the rank_affinity_flag.py shapes made safe — every
shared artifact path folds the rank in (the `host<rank>/` convention
scripts/launch_multihost.py established). Parsed only — never
imported."""

import json
import os


class TelemetrySession:  # stand-in sink shape; never imported
    def __init__(self, directory, **kwargs):
        self.directory = directory


def start_fleet_telemetry(base_dir, rank):
    return TelemetrySession(os.path.join(base_dir, f"host{rank}"))


def log_fleet_row(out_dir, rank, row):
    path = os.path.join(out_dir, f"metrics.host{rank}.jsonl")
    with open(path, "w") as f:
        json.dump(row, f)
