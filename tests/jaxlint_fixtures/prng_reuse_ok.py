"""jaxlint fixture (near miss, must NOT flag): the split-and-rebind
idiom — every binding is consumed exactly once. Parsed only — never
imported."""

import jax


def sample_pair(seed):
    key = jax.random.key(seed)
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (4,))
    b = jax.random.uniform(k2, (4,))
    return a + b


def noisy_rollout(key, steps):
    out = []
    for _ in range(steps):
        key, sub = jax.random.split(key)  # fresh subkey per iteration
        out.append(jax.random.normal(sub, ()))
    return out
