"""Near miss: the collective_discipline_flag.py shapes made safe — the
declared axis constant everywhere, branches keyed on fleet-uniform
values, a stop decision all-reduced as a vote instead of gating the
exchange, and a try whose handler re-raises. Parsed only — never
imported."""

import time

import jax

FLEET_AXIS = "dp"

mesh = jax.make_mesh((1,), (FLEET_AXIS,))


def reduce_declared_axis(x):
    return jax.lax.psum(x, FLEET_AXIS)  # the declared constant


def mode_gated_reduce(x, mode):
    if mode == "sync":  # fleet-uniform flag: every host agrees
        return jax.lax.psum(x, FLEET_AXIS)
    return x


def voted_stop_reduce(x, deadline):
    # The designed shape: the process-local deadline rides INTO the
    # collective as a vote; the break decision is its fleet-agreed sum.
    vote = 1.0 if time.monotonic() >= deadline else 0.0
    votes = jax.lax.psum(x * 0 + vote, FLEET_AXIS)
    return votes


def reraising_reduce(x):
    try:
        return jax.lax.pmean(x, FLEET_AXIS)
    except RuntimeError:
        raise  # a dead host takes its fleet slot down loudly
