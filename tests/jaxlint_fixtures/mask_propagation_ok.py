"""mask-propagation near-miss fixture: the two sanctioned seam shapes
(mask rides along; result sliced back) — must stay completely clean.

Parsed (never imported) by tests/test_jaxlint.py.
"""

from actor_critic_tpu.ops.pallas_scan import _pad_lanes
from actor_critic_tpu.utils.compile_cache import pad_to_bucket


def dispatch_with_mask(program, params, obs, buckets):
    padded, mask = pad_to_bucket(obs, buckets)
    # the mask crosses the seam with the array: the callee can keep
    # the discipline
    return program(params, padded, mask)


def dispatch_then_slice(program, params, obs, buckets, n):
    padded, _ = pad_to_bucket(obs, buckets)
    out = program(params, padded)
    # the serving act contract: only the valid prefix escapes
    return out[:n]


def lane_dispatch_sliced(kernel, Ep, E, rewards):
    (wide,) = _pad_lanes(Ep, rewards)
    adv = kernel(wide)
    # the Pallas contract: compute junk, slice it away
    return adv[:, :E]
