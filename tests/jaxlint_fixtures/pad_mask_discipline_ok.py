"""pad-mask-discipline near-miss fixture: the sanctioned masked /
sliced reduction idioms — must stay completely clean.

Parsed (never imported) by tests/test_jaxlint.py.
"""

import jax.numpy as jnp

from actor_critic_tpu.ops.pallas_scan import _pad_lanes
from actor_critic_tpu.utils.compile_cache import pad_to_bucket


def masked_bucket_mean(obs, buckets):
    padded, mask = pad_to_bucket(obs, buckets)
    # the mask multiply keeps the junk lanes at exactly zero, and the
    # floored denominator counts only valid rows
    return jnp.sum(padded * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def where_bucket_max(obs, buckets):
    padded, mask = pad_to_bucket(obs, buckets)
    # where-select: junk lanes replaced before the reduction sees them
    return jnp.max(jnp.where(mask > 0.5, padded, -jnp.inf))


def sliced_lane_sum(Ep, E, rewards):
    (wide,) = _pad_lanes(Ep, rewards)
    # inline valid-slice: the reduction only ever sees real lanes
    return jnp.sum(wide[:, :E])


def rebind_then_reduce(x, extra, n):
    wide = jnp.pad(x, (0, extra))
    valid = wide[:n]
    # the slice-back rebind clears the padded fact before the mean
    return jnp.mean(valid)
