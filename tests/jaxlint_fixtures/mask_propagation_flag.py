"""mask-propagation flag fixture: padded arrays crossing user
function/jit seams with the mask left behind and no slice-back.

Parsed (never imported) by tests/test_jaxlint.py.
"""

from actor_critic_tpu.ops.pallas_scan import _pad_lanes
from actor_critic_tpu.utils.compile_cache import pad_to_bucket


def dispatch_without_mask(program, params, obs, buckets):
    padded, mask = pad_to_bucket(obs, buckets)
    # the mask stays behind: the program cannot tell junk rows from
    # real ones, and nothing downstream cuts them away
    out = program(params, padded)
    return out


def lane_dispatch_unsliced(kernel, Ep, rewards):
    (wide,) = _pad_lanes(Ep, rewards)
    # the kernel's junk-lane output flows on at full Ep width
    return kernel(wide)
