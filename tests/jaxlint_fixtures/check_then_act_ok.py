"""Near miss: the same shapes as check_then_act_flag.py made safe —
double-checked locking for the lazy singleton (the unlocked fast-path
test is fine because the WRITE re-tests under the lock), and
test-and-set under the instance lock for the close guard."""

import threading

_LOCK = threading.Lock()
_LISTENER = None


def ensure_listener():
    global _LISTENER
    if _LISTENER is None:  # unlocked fast path...
        with _LOCK:
            if _LISTENER is None:  # ...re-tested under the lock
                _LISTENER = object()


class Closer:
    def __init__(self):
        self._lock = threading.Lock()
        self._closed = False

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
