"""jaxlint fixture (MUST FLAG donation-aliasing): donating a
checkpoint-restored buffer, and reading a donated name after the call.
Parsed only — never imported."""

import jax


def resume_and_step(ckpt, template):
    step = jax.jit(lambda s: s, donate_argnums=0)
    state = ckpt.restore(template)
    metrics = step(state)  # restore-aliased buffer donated
    return metrics


def double_use(step_fn, state):
    step = jax.jit(step_fn, donate_argnums=0)
    metrics = step(state)  # donates `state` ...
    return metrics, state  # ... then reads it again


def quantized_ingest_stale_read(encode, state, batch):
    """Codec-wrapper near-bug: the ring state is donated into the
    encoding ingest, then the OLD binding's quantizer stats are read —
    a buffer XLA already reused."""
    step = jax.jit(lambda s, b: encode(s, b), donate_argnums=0)
    new_state = step(state, batch)  # donates `state` ...
    return new_state, state.quant   # ... then reads the donated tree


def ring_enqueue_stale_gather(gather_block, ring_state, encoded, slot):
    """ISSUE 13 device-ring shape: the donated enqueue consumes the
    ring state, then the learner's gather is dispatched against the
    OLD binding — XLA already reused that buffer for the scatter."""
    enqueue = jax.jit(lambda s, e: e, donate_argnums=0)
    new_state = enqueue(ring_state, encoded)  # donates `ring_state` ...
    return new_state, gather_block(ring_state, slot)  # ... stale gather


def ring_enqueue_restored(ckpt, template, encoded):
    """Device-ring resume near-bug: a checkpoint-restored ring donated
    straight into the enqueue — the PR 4 restore-aliased class at the
    new call site."""
    enqueue = jax.jit(lambda s, e: e, donate_argnums=0)
    ring_state = ckpt.restore(template)
    return enqueue(ring_state, encoded)  # restore-aliased buffer donated
