"""precision-discipline flag fixture: every hazard class fires.

Parsed (never imported) by tests/test_jaxlint.py.
"""

import jax.numpy as jnp


def device_f64(shape):
    # float64 on the device namespace: silently demotes without x64,
    # doubles every buffer with it.
    return jnp.zeros(shape, jnp.float64)


def mixed_precision(shape):
    acts = jnp.zeros(shape, jnp.bfloat16)
    weights = jnp.ones(shape, jnp.float32)
    # bf16 × f32 promotes silently: the bf16 compute intent is lost.
    return acts * weights


def narrow_accumulator(shape):
    acts = jnp.zeros(shape, jnp.bfloat16)
    # the bf16-accumulator revert: sum accumulates IN bf16 (no dtype=)
    return jnp.sum(acts)


def decode(kind, q):
    # return dtype forks on the codec kind: raw passes through, the
    # rest return float32 — downstream dtypes depend on a config string
    if kind == "raw":
        return q
    return q.astype(jnp.float32)


def update_loss_terms(log_probs, ratio, adv):
    # the ISSUE 19 update shape, reverted: bf16 activations reach the
    # loss reductions with NO fp32 accumulator — entropy and the pg
    # term both accumulate in bf16 and truncate
    lp = log_probs.astype(jnp.bfloat16)
    r = ratio.astype(jnp.bfloat16)
    a = adv.astype(jnp.bfloat16)
    entropy = -jnp.mean(lp)
    pg = -jnp.mean(r * a)
    return pg, entropy
