"""Near miss: the same shapes as lock_discipline_flag.py made safe —
global mutations under a module lock, and the single-writer counter
carrying an audited `# jaxlint: thread-owned=<role>` annotation."""

import threading

_OPEN_LOCK = threading.Lock()
_OPEN_SPANS = []


class SpanService:
    def __init__(self):
        # jaxlint: thread-owned=collector (single writer: only this
        # service's own thread bumps the counter; readers tolerate a
        # one-block-stale value)
        self.blocks = 0
        self._thread = threading.Thread(
            target=self._run, name="collector", daemon=True
        )

    def start(self):
        self._thread.start()

    def enter(self, name):
        with _OPEN_LOCK:
            _OPEN_SPANS.append(name)

    def exit(self):
        with _OPEN_LOCK:
            _OPEN_SPANS.pop()

    def _run(self):
        while True:
            self.enter("step")
            self.blocks += 1  # annotated single-writer counter
            self.exit()
