# jaxlint: hot-module
"""jaxlint fixture (MUST FLAG host-sync): device syncs inside a step
loop of a hot module (opted in via the pragma above). Parsed only —
never imported."""

import numpy as np

import jax


def collect(pool, act, obs, steps, jit_update, state):
    for _ in range(steps):
        action = np.asarray(act(obs))  # device→host copy per step
        out = pool.step(action)
        state, metrics = jit_update(state, out)
        loss = float(metrics["loss"])  # sync per step
        jax.block_until_ready(state)  # hard fence per step
    return state, loss
