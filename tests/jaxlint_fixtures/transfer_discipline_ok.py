# jaxlint: hot-module
"""jaxlint fixture (near miss, must NOT flag): same hot module shapes,
but values stay on device inside the loops and the coercions/uploads
happen once outside them. Parsed only — never imported."""

import numpy as np

import jax
import jax.numpy as jnp


def collect(pool, act, obs, steps, jit_update, state):
    for _ in range(steps):
        action = act(obs)  # mirror/device path: no materialization
        out = pool.step(action)
        state, metrics = jit_update(state, out)
    history = {k: float(v) for k, v in metrics.items()}  # once, post-loop
    block = jnp.asarray(np.zeros((steps, 4)))  # host→device, not in a loop
    return state, history, block


def consume(ring, update, params, opt_state, key, n):
    """The device-plane consume: only the slot index scalar rides the
    dispatch — the steady-state loop touches no host arrays."""
    for _ in range(n):
        lease = ring.get()
        params, opt_state, _ = ring.run(
            lambda state: update(params, opt_state, state, lease.slot, key)
        )
        ring.release(lease)
    out = jax.device_get(params)  # once, after the loop
    return params, opt_state, out


def restage(run, state, blocks_staged):
    for b in blocks_staged:  # staged ONCE by the caller — resident
        state = run(state, b)
    return state
