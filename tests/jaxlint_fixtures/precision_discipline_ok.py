"""precision-discipline near-miss fixture: the sanctioned idioms of
each flagged class — must stay completely clean.

Parsed (never imported) by tests/test_jaxlint.py.
"""

import jax.numpy as jnp
import numpy as np


def host_f64_welford(shape):
    # float64 on HOST numpy is the sanctioned normalizer idiom.
    return np.zeros(shape, np.float64)


def explicit_cast(shape):
    acts = jnp.zeros(shape, jnp.bfloat16)
    weights = jnp.ones(shape, jnp.float32)
    # the explicit astype states the intent: no silent promotion
    return acts.astype(jnp.float32) * weights


def wide_accumulator(shape):
    acts = jnp.zeros(shape, jnp.bfloat16)
    # fp32 accumulator over the narrow operand: the sanctioned idiom
    return jnp.sum(acts, dtype=jnp.float32)


def config_selected_dtype(shape, bf16_compute):
    # the repo's bf16_compute selection: deliberately unresolvable,
    # both arms are possible — must not read as mixing
    dtype = jnp.bfloat16 if bf16_compute else jnp.float32
    return jnp.zeros(shape, dtype)


def decode(kind, q):
    # every branch normalizes to float32: no fork on the codec kind
    if kind == "raw":
        return q.astype(jnp.float32)
    return q.astype(jnp.float32)


def update_loss_terms(log_probs, ratio, adv):
    # ISSUE 19's sanctioned update spelling: bf16 operands are fine as
    # long as every loss reduction names its fp32 accumulator
    lp = log_probs.astype(jnp.bfloat16)
    r = ratio.astype(jnp.bfloat16)
    a = adv.astype(jnp.bfloat16)
    entropy = -jnp.mean(lp, dtype=jnp.float32)
    pg = -jnp.mean(r * a, dtype=jnp.float32)
    return pg, entropy
