# jaxlint: hot-module
"""jaxlint fixture (near miss, must NOT flag): same hot module shape,
but values stay on device inside the loop and the coercions happen once
after it. Parsed only — never imported."""

import numpy as np

import jax.numpy as jnp


def collect(pool, act, obs, steps, jit_update, state):
    for _ in range(steps):
        action = act(obs)  # mirror/device path: no materialization
        out = pool.step(action)
        state, metrics = jit_update(state, out)
    history = {k: float(v) for k, v in metrics.items()}  # once, post-loop
    block = jnp.asarray(np.zeros((steps, 4)))  # host→device, not a sync
    return state, history, block
