"""jaxlint fixture (near miss, must NOT flag): the same work folded
into the programs — the reduction runs in-jit, the eager op moved
inside the step, and the gather/update chain fused into ONE program
(the ppo.make_device_update_step shape). Parsed only — never
imported."""

from functools import partial

import jax
import jax.numpy as jnp


def make_fused_step(codecs):
    """Gather + scale + reduce + update inside one jitted program."""

    @partial(jax.jit, donate_argnums=0)
    def fused(state, slot):
        block = state.storage[slot]
        scaled = jnp.multiply(block, 0.5)
        total = jnp.sum(scaled)
        return state, total

    return fused


def consume(state, slots, codecs):
    fused = make_fused_step(codecs)
    for slot in slots:
        state, metrics = fused(state, slot)  # one program per iteration
    return state, metrics


def log_cadence_reduction(states, fused, state, slots):
    for slot in slots:
        state, metrics = fused(state, slot)
    return sum(float(m) for m in [metrics])  # once, after the loop
