"""Flag fixture (MUST FLAG collective-discipline, all three shapes):
an axis name no mesh declares, a collective gated on a process-local
branch, and a collective inside an exception-swallowing try. Parsed
only — never imported."""

import time

import jax

FLEET_AXIS = "dp"

mesh = jax.make_mesh((1,), (FLEET_AXIS,))


def reduce_bad_axis(x):
    return jax.lax.psum(x, "dq")  # typo'd axis: no mesh declares "dq"


def rank_gated_reduce(x, rank):
    if rank == 0:  # process-local predicate: only rank 0 enters
        return jax.lax.psum(x, "dp")  # ...the psum the others sit in
    return x


def deadline_gated_reduce(x, deadline):
    while time.monotonic() < deadline:  # wall clocks differ per host
        x = jax.lax.pmean(x, "dp")
    return x


def swallowed_reduce(x):
    try:
        return jax.lax.pmean(x, "dp")
    except RuntimeError:
        return x  # this host skips the exchange the fleet executes
