"""slice-before-commit near-miss fixture: the slice-back happens
before anything durable sees the buffer — must stay completely clean.

Parsed (never imported) by tests/test_jaxlint.py.
"""

from actor_critic_tpu.utils.compile_cache import pad_to_bucket


def enqueue_valid(ring, obs, buckets, n):
    padded, _ = pad_to_bucket(obs, buckets)
    # inline valid-slice at the commit point
    ring.put(padded[:n], version=1)


def respond_valid(sock, obs, buckets, n):
    padded, _ = pad_to_bucket(obs, buckets)
    valid = padded[:n]
    # the rebind carries only real rows into the send
    sock.send(valid)
