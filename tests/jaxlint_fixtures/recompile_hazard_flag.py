"""jaxlint fixture (MUST FLAG recompile-hazard): jit constructed inside
a loop, and a len()-derived Python scalar fed to a jitted call. Parsed
only — never imported."""

import jax
import jax.numpy as jnp


def per_item(xs):
    out = []
    for x in xs:
        f = jax.jit(lambda a: a + 1)  # fresh callable every iteration
        out.append(f(x))
    return out


tail_update = jax.jit(lambda a, n: a * 1.0)


def dispatch_tail(batch):
    n = len(batch)
    return tail_update(jnp.asarray(batch), n)  # len-derived scalar arg
