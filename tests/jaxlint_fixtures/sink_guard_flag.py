"""sink-guard flag fixture: fragile sinks without finiteness gates.

Parsed (never imported) by tests/test_jaxlint.py.
"""

import json

PARAMS_ON_DISK = {}


def emit_row(fh, row):
    # allow_nan=False raises on the first NaN and the row vanishes —
    # the telemetry sampler crash class
    fh.write(json.dumps(row, allow_nan=False) + "\n")


def write_params(mailbox_dir, rank, version, params):
    # ungated mailbox publish: a nan snapshot diffuses to every peer
    PARAMS_ON_DISK[(mailbox_dir, rank)] = (version, params)


class Publisher:
    def publish(self, params, version):
        # ungated behavior-params publish: every actor inherits the nan
        self._params = (version, params)


class Store:
    def swap(self, policy_id, params, version=None):
        # ungated gateway swap: clients get nan actions next dispatch
        self._handles[policy_id] = (version, params)
        return self._handles[policy_id]


class Checkpointer:
    def save(self, step, state):
        # ungated checkpoint commit: every future resume inherits it
        self._steps[step] = state
