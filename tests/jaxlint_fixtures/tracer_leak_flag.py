"""jaxlint fixture (MUST FLAG tracer-leak): Python control flow on a
traced value inside jit. Parsed only — never imported."""

import jax


@jax.jit
def relu_branch(x):
    if x > 0:  # traced value in a Python `if`
        return x
    return -x


def make_step(cfg):
    def step(state):
        total = state.sum()
        while total > 0:  # traced value drives a Python `while`
            total = total - 1.0
        return total

    return jax.jit(step)
