"""Near miss: the same shapes as publish_aliasing_flag.py made safe —
snapshots (`.copy()` / `np.array`) at every channel boundary, a
per-iteration allocation instead of a republished slot, and the
consumer snapshotting before `release`."""

import numpy as np


class BlockProducer:
    def __init__(self, queue):
        self._queue = queue
        self._slot = np.zeros((8, 4), np.float32)

    def run(self):
        while True:
            self._slot[...] = 1.0
            self._queue.put({"obs": self._slot.copy()})
            self._queue.put(np.array(self._slot[:4]))


def publish_loop(publisher, n):
    for v in range(n):
        buf = np.full((4,), float(v), np.float32)  # fresh every pass
        publisher.publish(buf, version=v)


def drain(queue, update, params):
    while True:
        block = queue.get()
        arrays = {k: np.array(v) for k, v in block.arrays.items()}
        queue.release(block)  # safe: arrays are snapshots
        params = update(params, arrays)
