# jaxlint: hot-module
"""jaxlint fixture (MUST FLAG transfer-discipline): host<->device
crossings inside steady-state loop bodies — the host-sync sync family
(absorbed by this pass, ISSUE 15) plus the device_get/upload kinds it
added. Parsed only — never imported."""

import numpy as np

import jax
import jax.numpy as jnp


def collect(pool, act, obs, steps, jit_update, state):
    for _ in range(steps):
        action = np.asarray(act(obs))  # device→host copy per step
        out = pool.step(action)
        state, metrics = jit_update(state, out)
        loss = float(metrics["loss"])  # sync per step
        jax.block_until_ready(state)  # hard fence per step
    return state, loss


def consume(queue, update, params, opt_state, key, n):
    """The pre-PR-13 host-gather learner shape: every consumed block is
    fetched to host and re-uploaded inside the steady-state loop."""
    for _ in range(n):
        block = queue.get()
        host = jax.device_get(block.arrays)  # device→host gather per block
        arrays = {k: jnp.array(v) for k, v in host.items()}  # re-upload
        params, opt_state, _ = update(params, opt_state, arrays, key)
    return params, opt_state


def restage(run, state, blocks):
    for b in blocks:
        staged = jax.device_put(b)  # host→device upload per iteration
        state = run(state, staged)
    return state
