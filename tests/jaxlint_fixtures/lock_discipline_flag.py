"""Flag fixture: compound writes to cross-thread shared state with no
lock held — the PR 6 open-span-stack bug shape, twice: a module-global
stack mutated from service methods that run on actor threads, and a
threaded class whose loop bumps a shared counter unlocked."""

import threading

_OPEN_SPANS = []  # shared by every service thread


class SpanService:
    def __init__(self):
        self.blocks = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()

    def enter(self, name):
        _OPEN_SPANS.append(name)  # interleaved push from actor threads

    def exit(self):
        _OPEN_SPANS.pop()  # ...pops another thread's entry

    def _run(self):
        while True:
            self.enter("step")
            self.blocks += 1  # unlocked read-modify-write
            self.exit()
