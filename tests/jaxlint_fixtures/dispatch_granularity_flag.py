"""jaxlint fixture (MUST FLAG dispatch-granularity): per-step work
dispatched as many tiny programs — a Python reduction over device
values, an eager jnp op in the step loop, and a two-program
gather/update chain one fused program should absorb. Parsed only —
never imported."""

import jax
import jax.numpy as jnp

step = jax.jit(lambda s, b: s)
gather = jax.jit(lambda s, i: s)


def python_reduction(states, blocks):
    for b in blocks:
        total = sum(jnp.sum(s) for s in states)  # one dispatch per element
        metrics = step(total, b)
    return metrics


def eager_per_step(state, blocks):
    for b in blocks:
        scaled = jnp.multiply(b, 0.5)  # its own XLA program every step
        metrics = step(state, scaled)
    return metrics


def two_program_chain(state, slots, key):
    for slot in slots:
        block = gather(state, slot)  # program 1 ...
        metrics = step(state, block)  # ... program 2, every iteration
    return metrics
