"""sink-guard near-miss fixture: the same sinks carrying the
sanctioned gates — must stay completely clean.

Parsed (never imported) by tests/test_jaxlint.py.
"""

from actor_critic_tpu.utils import numguard
from actor_critic_tpu.utils.numguard import safe_json_row

PARAMS_ON_DISK = {}


def emit_row(fh, row):
    # non-finite floats become null; the row always serializes
    fh.write(safe_json_row(row) + "\n")


def write_params(mailbox_dir, rank, version, params):
    numguard.check_finite(params, "mailbox publish")
    PARAMS_ON_DISK[(mailbox_dir, rank)] = (version, params)


class Publisher:
    def publish(self, params, version):
        numguard.check_finite(params, "behavior-params publish")
        self._params = (version, params)


class Store:
    def swap(self, policy_id, params, version=None):
        numguard.check_finite(params, "policy swap")
        self._handles[policy_id] = (version, params)
        return self._handles[policy_id]


class Checkpointer:
    def save(self, step, state):
        numguard.check_finite(state, "checkpoint commit")
        self._steps[step] = state
