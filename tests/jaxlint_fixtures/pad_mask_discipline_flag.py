"""pad-mask-discipline flag fixture: reductions over padding-widened
axes with no mask and no valid-slice — every producer class fires.

Parsed (never imported) by tests/test_jaxlint.py.
"""

import jax.numpy as jnp

from actor_critic_tpu.ops.pallas_scan import _pad_lanes
from actor_critic_tpu.utils.compile_cache import pad_to_bucket


def unmasked_bucket_mean(obs, buckets):
    padded, mask = pad_to_bucket(obs, buckets)
    # mean over the widened batch axis without the mask: silently
    # rescales by n/bucket (7 rows in a 128 bucket -> off 18x)
    return jnp.mean(padded)


def unmasked_lane_sum(Ep, rewards):
    (wide,) = _pad_lanes(Ep, rewards)
    # the Mosaic junk lanes are summed in with the real envs
    return jnp.sum(wide)


def unmasked_raw_pad_max(x, extra):
    wide = jnp.pad(x, (0, extra))
    # argmax can land IN the pad: zeros beat negative valid entries
    return jnp.argmax(wide)
