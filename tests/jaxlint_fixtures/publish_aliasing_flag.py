"""Flag fixture: ndarray views of recycled storage crossing a thread
channel — both sides of the PR 6 zero-copy race. Producer side: a
preallocated slot (and a view of it) handed to `.put()`/`.publish()`
without a snapshot. Consumer side: `np.asarray` aliases a block that is
`release`d back to its slot pool in the same scope."""

import numpy as np


class BlockProducer:
    def __init__(self, queue):
        self._queue = queue
        self._slot = np.zeros((8, 4), np.float32)

    def run(self):
        while True:
            self._slot[...] = 1.0
            self._queue.put({"obs": self._slot})  # slot, not snapshot
            self._queue.put(self._slot[:4])  # view of the slot


def publish_loop(publisher, n):
    buf = np.zeros((4,), np.float32)  # allocated once...
    for v in range(n):
        buf[:] = v
        publisher.publish(buf, version=v)  # ...republished every pass


def drain(queue, update, params):
    while True:
        block = queue.get()
        arrays = {k: np.asarray(v) for k, v in block.arrays.items()}
        queue.release(block)  # slot recycles under the asarray views
        params = update(params, arrays)
