"""jaxlint fixture (MUST FLAG donation-discipline): a recycled buffer
through an undonated compiled program, and a donated-then-read ALIAS
near-miss the donation-aliasing pass cannot see. Parsed only — never
imported."""

import jax


def make_update_step(cfg):
    """Factory shape (the repo's convention): the jit lives here, the
    dispatch loop lives in the caller — and donation was forgotten."""

    def update(state, block):
        return state

    return jax.jit(update)


def learner_loop(cfg, state, blocks):
    update = make_update_step(cfg)
    for block in blocks:
        # recycled every iteration (result rebinds the argument) but
        # the program copy-preserves the input instead of reusing it
        state = update(state, block)
    return state


def stale_view(step_fn, state, block):
    step = jax.jit(step_fn, donate_argnums=0)
    quant = state["quant"]  # alias INTO the donated tree ...
    state = step(state, block)  # ... donated (and properly rebound)
    return state, quant  # ... but the view reads the reused buffer
