"""jaxlint fixture (near miss, must NOT flag): the same donation shape,
but the restored state is re-placed before donation and the donated
name is rebound by the call. Parsed only — never imported."""

import jax


def resume_and_step(ckpt, template, uncommit):
    step = jax.jit(lambda s: s, donate_argnums=0)
    state = uncommit(ckpt.restore(template))  # re-placed: jax-owned
    state = step(state)  # rebound by the donating call
    return state


def loop_step(step_fn, state, n):
    step = jax.jit(step_fn, donate_argnums=0)
    for _ in range(n):
        state, metrics = step(state)  # rebound every iteration
    return state, metrics
