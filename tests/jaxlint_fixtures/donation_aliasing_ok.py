"""jaxlint fixture (near miss, must NOT flag): the same donation shape,
but the restored state is re-placed before donation and the donated
name is rebound by the call. Parsed only — never imported."""

import jax


def resume_and_step(ckpt, template, uncommit):
    step = jax.jit(lambda s: s, donate_argnums=0)
    state = uncommit(ckpt.restore(template))  # re-placed: jax-owned
    state = step(state)  # rebound by the donating call
    return state


def loop_step(step_fn, state, n):
    step = jax.jit(step_fn, donate_argnums=0)
    for _ in range(n):
        state, metrics = step(state)  # rebound every iteration
    return state, metrics


def quantized_ingest(encode, decode, state, batch, key):
    """The ISSUE 8 codec-wrapper shape: encode-on-add / decode-on-sample
    closed over by a donating jit, the donated name rebound by the call
    — the codec layer must not break donation discipline."""

    def ingest(s, b):
        q = encode(s.quant, b)  # pure: quantize, then in-place scatter
        storage = jax.tree.map(lambda st, x: st.at[0].set(x), s.storage, q)
        return s._replace(storage=storage)

    step = jax.jit(ingest, donate_argnums=0)
    state = step(state, batch)  # rebound by the donating call
    sampled = decode(state.quant, state.storage)  # reads the NEW binding
    return state, sampled


def ring_enqueue_then_gather(gather_block, ring_state, blocks, slot):
    """ISSUE 13 device-ring discipline (must NOT flag): the donated
    enqueue REBINDS the ring state every put, and the learner's gather
    reads the current binding — the DeviceTrajRing lock serializes the
    two dispatches, so no stale handle ever exists."""
    enqueue = jax.jit(lambda s, e: e, donate_argnums=0)
    for encoded in blocks:
        ring_state = enqueue(ring_state, encoded)  # rebound per put
    return ring_state, gather_block(ring_state, slot)  # NEW binding
