"""Flag fixture: unlocked read-test-write windows on shared state —
the lazy-singleton shape on a module global, and the
`if closed: return` guard shape on an instance flag. Two threads pass
either test together before one writes."""

import threading

_LISTENER = None


def ensure_listener():
    global _LISTENER
    if _LISTENER is None:  # both threads see None...
        _LISTENER = object()  # ...and both install


class Closer:
    def __init__(self):
        self._lock = threading.Lock()
        self._closed = False

    def close(self):
        if self._closed:  # both callers pass...
            return
        self._closed = True  # ...and teardown below runs twice
