"""Flag fixture (MUST FLAG mailbox-protocol, all four shapes): a
non-atomic publish of the consumed path, an atomic publish missing
fsync with a collision-prone shared tmp name, a torn-intolerant
consumer, and a global (non-per-peer) version clock. Parsed only —
never imported."""

import os

import numpy as np


def snapshot_file(mailbox_dir, who):
    return os.path.join(mailbox_dir, f"host{who}", "params.npz")


def publish_direct(mailbox_dir, who, payload):
    path = snapshot_file(mailbox_dir, who)
    with open(path, "wb") as f:  # torn under SIGKILL: readers see half
        np.savez(f, **payload)
    return path


def publish_shared_tmp(mailbox_dir, who, payload):
    path = snapshot_file(mailbox_dir, who)
    tmp = os.path.join(mailbox_dir, "pending.tmp")  # shared across ranks
    with open(tmp, "wb") as f:  # and no fsync before the rename
        np.savez(f, **payload)
    os.replace(tmp, path)
    return path


def consume_intolerant(mailbox_dir, who):
    path = snapshot_file(mailbox_dir, who)
    try:
        with np.load(path) as z:  # truncated npz raises BadZipFile
            return {k: z[k] for k in z.files}
    except OSError:
        return None


def consume_global_clock(mailbox_dir, schedule):
    newest = -1  # ONE clock for every peer: fast peers mute slow ones
    out = []
    for peer in schedule:
        snap = consume_intolerant(mailbox_dir, peer)
        if snap is None:
            continue
        version = int(snap["version"])
        if version > newest:
            newest = version
            out.append((peer, version))
    return out
