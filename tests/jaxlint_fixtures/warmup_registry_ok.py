"""jaxlint fixture (near miss, must NOT flag): the same jit entry-point
shape, but its key IS in the registry the test injects. Parsed only —
never imported."""

import jax


def make_step(cfg):
    @jax.jit
    def step(state):
        return state

    return step
