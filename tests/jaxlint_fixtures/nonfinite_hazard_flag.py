"""nonfinite-hazard flag fixture: every hazard class fires.

Parsed (never imported) by tests/test_jaxlint.py.
"""

import jax.numpy as jnp


def unguarded_log(x):
    # one zero/negative element is -inf/nan in the loss
    return jnp.log(x)


def unguarded_sqrt(v):
    # a variance estimate slightly below zero is nan
    return jnp.sqrt(v)


def unguarded_squashed_log_prob(action):
    # arctanh of a stored squashed action at exactly ±1 is ±inf
    pre_tanh = jnp.arctanh(action)
    return -0.5 * pre_tanh * pre_tanh


def unguarded_ratio(log_prob, old_log_prob, adv):
    # the PPO/V-trace surrogate shape: policy drift overflows to inf,
    # inf × 0 advantage is nan
    ratio = jnp.exp(log_prob - old_log_prob)
    return ratio * adv


def fresh_scale_seed(shape):
    # the PR 8 class: a 1.0 seed floors the quantization step forever
    scale = jnp.ones(shape)
    return {"mean": jnp.zeros(shape), "scale": scale}


def unfloored_normalize(x):
    total = jnp.sum(x)
    # a constant batch makes the denominator exactly zero
    return x / total
