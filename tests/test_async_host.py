"""Async actor–learner decoupling (ISSUE 6): lockstep equivalence at
queue depth 1, straggler immunity, drop-oldest back-pressure through the
driver, V-trace correction semantics, the heterogeneous straggler-shard
env plumbing, and the steady-state compile-count regression contract."""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from actor_critic_tpu.algos import ppo
from actor_critic_tpu.algos.common import corrected_advantages
from actor_critic_tpu.telemetry import profiler
from actor_critic_tpu.utils import compile_cache

gym = pytest.importorskip("gymnasium")

from actor_critic_tpu.envs.host_pool import HostEnvPool  # noqa: E402
from actor_critic_tpu.envs.sleep_pad import (  # noqa: E402
    QUALIFIED_CARTPOLE_ID,
    QUALIFIED_ENV_ID,
)


def _tree_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


# ------------------------------------------------------- lockstep equivalence

@pytest.mark.parametrize(
    "data_plane",
    ["host", "device"],
    ids=["host_plane", "device_plane"],
)
@pytest.mark.parametrize(
    "epochs,minibatches",
    [(2, 2), (1, 1)],
    ids=["ppo_shaped", "a2c_shaped"],  # 1 epoch x 1 full-batch mb = A2C-style
)
def test_async_depth1_is_bitwise_lockstep(epochs, minibatches, data_plane):
    """Async mode with one actor, queue depth 1, updates-per-block 1 and
    correction='none' must be bit-for-bit the current train_host
    pipeline (params AND optimizer state) — the refactor is pure
    decoupling, not a silent algorithm change. The device data plane
    (ISSUE 13, fp32 codec: the block round-trips the HBM ring and is
    gathered+decoded in-jit) must preserve the same bits — relocation,
    not a new algorithm."""
    cfg = ppo.PPOConfig(
        num_envs=4, rollout_steps=8, epochs=epochs,
        num_minibatches=minibatches, hidden=(16,),
    )
    pool = HostEnvPool("CartPole-v1", num_envs=4, seed=0)
    try:
        p_lock, o_lock, _ = ppo.train_host(
            pool, cfg, num_iterations=3, seed=0, log_every=0
        )
    finally:
        pool.close()
    pool = HostEnvPool("CartPole-v1", num_envs=4, seed=0)
    try:
        p_async, o_async, hist = ppo.train_host_async(
            [pool], cfg, 3, seed=0, log_every=0, updates_per_block=1,
            queue_depth=1, correction="none", strict_lockstep=True,
            data_plane=data_plane, plane_codec="fp32",
        )
    finally:
        pool.close()
    assert _tree_equal(p_lock, p_async)
    assert _tree_equal(o_lock, o_async)


# ----------------------------------------------------------- straggler / drops

def test_straggler_actor_does_not_stall_learner():
    """One sleep-padded actor must slow only its own contribution: the
    learner's N updates complete far inside the lockstep bound (which
    pays the straggler's pace on every block)."""
    cfg = ppo.PPOConfig(
        num_envs=2, rollout_steps=4, epochs=1, num_minibatches=1,
        hidden=(8,),
    )
    iters, pad = 8, 0.3
    # Lockstep lower bound: every block waits for the padded envs —
    # K steps x E envs x pad seconds each (in-process SyncVectorEnv
    # steps envs serially).
    lockstep_bound = iters * cfg.rollout_steps * 2 * pad  # 19.2 s
    pools = [
        HostEnvPool(
            QUALIFIED_ENV_ID, 2, seed=0, normalize_obs=False,
            normalize_reward=False, env_kwargs={"sleep_s": pad},
        ),
        HostEnvPool(
            QUALIFIED_ENV_ID, 2, seed=100003, normalize_obs=False,
            normalize_reward=False, env_kwargs={"sleep_s": 0.0},
        ),
    ]
    try:
        t0 = time.perf_counter()
        _, _, hist = ppo.train_host_async(
            pools, cfg, iters, seed=0, log_every=1, queue_depth=2,
            max_staleness=None, correction="vtrace",
        )
        wall = time.perf_counter() - t0
    finally:
        for p in pools:
            p.close()
    assert len(hist) == iters
    # Generous compile slack, still far under the lockstep bound.
    assert wall < lockstep_bound * 0.6, (
        f"learner stalled: wall {wall:.1f}s vs lockstep bound "
        f"{lockstep_bound:.1f}s"
    )
    last = hist[-1][1]
    assert np.isfinite(last["loss"]) and np.isfinite(last["mean_rho"])
    # Fairness signal: most consumed blocks came from the FAST actor
    # (id 1) — the straggler contributes, it just can't dominate.
    from_fast = sum(1 for _, m in hist if m["block_actor"] == 1)
    assert from_fast >= iters // 2, [m["block_actor"] for _, m in hist]


def test_actor_death_surfaces_while_queue_is_fed():
    """A mid-run actor crash must raise even though the SURVIVING actor
    keeps the queue non-empty — a silently halved fleet is not a
    healthy run."""
    cfg = ppo.PPOConfig(
        num_envs=2, rollout_steps=4, epochs=1, num_minibatches=1,
        hidden=(8,),
    )
    pools = [
        # Actor 0's envs blow up inside the first collection block.
        HostEnvPool(
            QUALIFIED_ENV_ID, 2, seed=0, normalize_obs=False,
            normalize_reward=False, env_kwargs={"crash_at_step": 3},
        ),
        HostEnvPool(
            QUALIFIED_ENV_ID, 2, seed=100003, normalize_obs=False,
            normalize_reward=False,
        ),
    ]
    try:
        with pytest.raises(RuntimeError, match="actor 0 died"):
            ppo.train_host_async(
                pools, cfg, 200, seed=0, log_every=0, queue_depth=2,
                correction="vtrace",
            )
    finally:
        for p in pools:
            p.close()


def test_backpressure_drops_oldest_through_driver():
    """A producer that outruns the learner must never block: the queue
    recycles oldest blocks and the drop counters surface in the log
    rows."""
    cfg = ppo.PPOConfig(
        num_envs=2, rollout_steps=4, epochs=2, num_minibatches=2,
        hidden=(16,),
    )
    pool = HostEnvPool("CartPole-v1", 2, seed=0)
    try:
        _, _, hist = ppo.train_host_async(
            [pool], cfg, 6, seed=0, log_every=1, updates_per_block=4,
            queue_depth=1, max_staleness=None, correction="vtrace",
        )
    finally:
        pool.close()
    last = hist[-1][1]
    assert last["queue_drops_full"] > 0  # actor ran ahead, nothing blocked
    assert last["env_steps"] >= last["consumed_env_steps"]


# ------------------------------------------------------- V-trace correction

def test_corrected_advantages_on_policy_reduction():
    """With pi == mu the V-trace value targets equal the GAE returns for
    any lambda, and the pg advantages coincide at lambda=1 (canonical
    IMPALA) — async correction degrades gracefully to on-policy."""
    rng = np.random.default_rng(0)
    T, E = 12, 6
    lp = jnp.asarray(rng.normal(size=(T, E)) * 0.3, jnp.float32)
    rewards = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    values = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    dones = jnp.asarray(rng.random((T, E)) < 0.1, jnp.float32)
    boot = jnp.asarray(rng.normal(size=(E,)), jnp.float32)

    for lam in (1.0, 0.9):
        adv_v, ret_v, rho = corrected_advantages(
            lp, lp, rewards, values, dones, boot, 0.99, lam,
            correction="vtrace",
        )
        adv_g, ret_g, _ = corrected_advantages(
            lp, lp, rewards, values, dones, boot, 0.99, lam,
            correction="none",
        )
        np.testing.assert_allclose(
            np.asarray(ret_v), np.asarray(ret_g), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(float(rho), 1.0, rtol=1e-6)
        if lam == 1.0:
            np.testing.assert_allclose(
                np.asarray(adv_v), np.asarray(adv_g), rtol=1e-4, atol=1e-5
            )


def test_vtrace_correction_recovers_on_policy_return_under_staleness():
    """Forced staleness: trajectories SAMPLED under a behavior policy,
    corrected toward a different target policy. With wide clips the
    V-trace value estimate is per-decision importance sampling, so its
    mean must match the target policy's analytic return within sampling
    tolerance; with the canonical rho_bar=c_bar=1 clips (and a zero
    value baseline) the estimator's expectation is also available in
    closed form — both ends of the correction are checked against
    analytic ground truth."""
    rng = np.random.default_rng(1)
    T, E, gamma = 8, 8192, 0.9
    p_b, p_t = 0.5, 0.8  # behavior samples 50/50; target prefers a=1
    actions = (rng.random((T, E)) < p_b).astype(np.float32)
    behavior_lp = np.where(actions == 1.0, np.log(p_b), np.log(1 - p_b))
    target_lp = np.where(actions == 1.0, np.log(p_t), np.log(1 - p_t))
    rewards = actions  # r_t = a_t
    zeros = np.zeros((T, E), np.float32)

    def estimate(rho_bar, c_bar):
        _, vs, _ = corrected_advantages(
            jnp.asarray(target_lp, jnp.float32),
            jnp.asarray(behavior_lp, jnp.float32),
            jnp.asarray(rewards), jnp.asarray(zeros), jnp.asarray(zeros),
            jnp.zeros((E,), jnp.float32), gamma, 1.0,
            rho_bar=rho_bar, c_bar=c_bar, correction="vtrace",
        )
        return float(np.asarray(vs)[0].mean())

    horizon = (1 - gamma**T) / (1 - gamma)
    on_policy = p_t * horizon      # analytic E_pi[G] = 4.556
    unclipped = estimate(1e9, 1e9)
    assert abs(unclipped - on_policy) / on_policy < 0.05, (
        unclipped, on_policy
    )
    # rho_bar=c_bar=1 on a zero value baseline: a=1 ratios (1.6) clip to
    # 1, a=0 ratios stay 0.4, so E[min(rho,1)] = 0.7 per prefix step and
    # E[min(rho_t,1) r_t] = 0.5 — term t is gamma^t * 0.7^t * 0.5.
    clipped_expect = 0.5 * sum((gamma * 0.7) ** t for t in range(T))
    clipped = estimate(1.0, 1.0)
    assert abs(clipped - clipped_expect) / clipped_expect < 0.05, (
        clipped, clipped_expect
    )
    assert clipped < unclipped  # the clip bounds variance by shedding mass


# ------------------------------------------------- straggler-shard plumbing

def test_worker_env_kwargs_heterogeneous_shards():
    """Per-worker constructor overrides: worker 0 sleep-padded, worker 1
    fast — the straggler-injection mechanism the async bench uses."""
    pool = HostEnvPool(
        QUALIFIED_ENV_ID, 4, seed=0, workers=2,
        normalize_obs=False, normalize_reward=False,
        worker_env_kwargs=[{"sleep_s": 0.05}, None],
    )
    try:
        pool.reset()
        acts = np.zeros(4, np.int64)
        for _ in range(3):
            pool.step(acts)
        stats = pool.worker_stats()
        assert stats[0]["busy_s"] > 0.05 * 2 * 3 * 0.5  # padded shard
        assert stats[1]["busy_s"] < stats[0]["busy_s"] / 3
    finally:
        pool.close()


def test_worker_env_kwargs_validation():
    from actor_critic_tpu.envs.shard_pool import ShardedVecEnv

    with pytest.raises(ValueError, match="worker_env_kwargs"):
        ShardedVecEnv(
            QUALIFIED_ENV_ID, 4, workers=2, worker_env_kwargs=[{}]
        )
    with pytest.raises(ValueError, match="worker_env_kwargs"):
        HostEnvPool(
            QUALIFIED_ENV_ID, 4, workers=1, worker_env_kwargs=[{}]
        )


def test_sleep_pad_cartpole_is_real_cartpole():
    env = gym.make(QUALIFIED_CARTPOLE_ID, sleep_s=0.0)
    obs, _ = env.reset(seed=0)
    assert obs.shape == (4,)
    ref = gym.make("CartPole-v1")
    ref_obs, _ = ref.reset(seed=0)
    np.testing.assert_array_equal(obs, ref_obs)
    env.close()
    ref.close()


# ------------------------------------------- off-policy actor services

def test_offpolicy_async_ddpg_trains_and_accounts_steps():
    """ISSUE 9 satellite: --async-actors is no longer PPO-only — the
    DDPG/TD3 host loop drives collection through ActorService threads
    and the learner ingests queued blocks into the replay ring (replay
    absorbs behavior staleness; no correction knob)."""
    from actor_critic_tpu.algos import ddpg

    cfg = ddpg.DDPGConfig(
        num_envs=2, steps_per_iter=4, updates_per_iter=1,
        buffer_capacity=256, batch_size=8, warmup_steps=16, hidden=(16,),
    )
    pools = [
        HostEnvPool(
            "Pendulum-v1", 1, seed=0,
            normalize_obs=False, normalize_reward=False,
        ),
        HostEnvPool(
            "Pendulum-v1", 1, seed=100003,
            normalize_obs=False, normalize_reward=False,
        ),
    ]
    try:
        learner, hist = ddpg.train_host_async(
            pools, cfg, 12, seed=0, log_every=1,
            eval_every=6, eval_steps=50,
        )
    finally:
        for p in pools:
            p.close()
    rows = dict(hist)
    assert sorted(rows) == list(range(1, 13))
    last = rows[12]
    assert np.isfinite(last["critic_loss"]) and np.isfinite(last["q_mean"])
    # The fleet collected at least what the learner consumed, and the
    # ring really ingested the consumed blocks.
    assert last["env_steps"] >= last["consumed_env_steps"]
    assert int(learner.replay.size) > 0
    assert "eval_return" in rows[6] and np.isfinite(rows[6]["eval_return"])


def test_offpolicy_async_sac_smoke():
    from actor_critic_tpu.algos import sac

    cfg = sac.SACConfig(
        num_envs=1, steps_per_iter=4, updates_per_iter=1,
        buffer_capacity=128, batch_size=8, warmup_steps=8, hidden=(16,),
    )
    pool = HostEnvPool(
        "Pendulum-v1", 1, seed=0,
        normalize_obs=False, normalize_reward=False,
    )
    try:
        learner, hist = sac.train_host_async(
            [pool], cfg, 6, seed=0, log_every=1,
        )
    finally:
        pool.close()
    assert len(hist) == 6
    assert np.isfinite(hist[-1][1]["critic_loss"])
    assert int(learner.replay.size) > 0


# --------------------------------------------- compile-count regression

def test_async_learner_steady_state_zero_recompiles(tmp_path):
    """ISSUE 6 acceptance: the async learner's corrected-update program
    is AOT-warmed (registry planner), the loop's first dispatch hits the
    persistent cache, and steady state compiles nothing — blocks are the
    PR 4 fixed-shape buckets, so zero new XLA programs."""
    if not profiler.ensure_compile_introspection():
        pytest.skip("jax compile funnel unavailable in this jax version")
    cfg = ppo.PPOConfig(
        num_envs=4, rollout_steps=8, epochs=1, num_minibatches=2,
        hidden=(16,),
    )
    pools = [
        HostEnvPool("CartPole-v1", 2, seed=0),
        HostEnvPool("CartPole-v1", 2, seed=100003),
    ]
    try:
        with compile_cache.temporary_cache(tmp_path / "cc"):
            ctx = compile_cache.WarmupContext(
                algo="ppo", fused=False, spec=pools[0].spec, cfg=cfg,
                eval_every=0, overlap=True, async_actors=2,
                async_correction="vtrace",
            )
            plan = compile_cache.plan_warmup(ctx)
            # Acting/eval mirror on the host; the corrected update is
            # the ONLY device program an async run dispatches.
            assert [n for n, _ in plan] == ["ppo.make_async_update_step"]
            n0 = profiler.compile_event_count()
            runner = compile_cache.WarmupRunner(plan).start()
            assert runner.wait(300) and "error" not in runner.results[0], (
                runner.results
            )

            counts = {}

            def log_fn(it, m):
                counts[it] = profiler.compile_event_count()

            ppo.train_host_async(
                pools, cfg, 4, seed=0, log_every=1, log_fn=log_fn,
                correction="vtrace",
            )
    finally:
        for p in pools:
            p.close()

    from conftest import new_compile_records

    records = new_compile_records(n0)
    update_evs = [r for r in records if r["name"] == "jit_async_update"]
    real = [r for r in update_evs if not r.get("cache_hit")]
    assert len(real) == 1, update_evs  # warmup's one true compile
    assert any(r.get("cache_hit") for r in update_evs), update_evs
    # Steady state: iterations past the second compile NOTHING.
    assert counts[4] == counts[2], records


def test_device_plane_steady_state_zero_recompiles(tmp_path):
    """ISSUE 13 acceptance: the device data plane's BOTH new jitted
    programs — the donated ring enqueue and the gather+decode+update —
    are AOT-warmed (registry planners), and steady state compiles
    nothing: blocks are fixed-shape ring slots, the slot index is a
    traced scalar, and the calibrating quant re-uploads are
    shape-stable."""
    if not profiler.ensure_compile_introspection():
        pytest.skip("jax compile funnel unavailable in this jax version")
    cfg = ppo.PPOConfig(
        num_envs=4, rollout_steps=8, epochs=1, num_minibatches=2,
        hidden=(16,),
    )
    pools = [
        HostEnvPool("CartPole-v1", 2, seed=0),
        HostEnvPool("CartPole-v1", 2, seed=100003),
    ]
    try:
        with compile_cache.temporary_cache(tmp_path / "cc"):
            ctx = compile_cache.WarmupContext(
                algo="ppo", fused=False, spec=pools[0].spec, cfg=cfg,
                eval_every=0, overlap=True, async_actors=2,
                async_correction="vtrace", data_plane="device",
                plane_codec="int8", queue_depth=4,
            )
            plan = compile_cache.plan_warmup(ctx)
            # The device plane's two programs — and NOT the host
            # plane's argument-fed update.
            assert [n for n, _ in plan] == [
                "ppo.make_device_update_step", "ring.make_enqueue",
            ]
            n0 = profiler.compile_event_count()
            runner = compile_cache.WarmupRunner(plan).start()
            assert runner.wait(300), runner.results
            assert not any("error" in r for r in runner.results), (
                runner.results
            )

            counts = {}

            def log_fn(it, m):
                counts[it] = profiler.compile_event_count()

            ppo.train_host_async(
                pools, cfg, 4, seed=0, log_every=1, log_fn=log_fn,
                correction="vtrace", data_plane="device",
                plane_codec="int8", queue_depth=4,
            )
    finally:
        for p in pools:
            p.close()

    from conftest import new_compile_records

    records = new_compile_records(n0)
    for name in ("jit_device_update", "jit_enqueue"):
        evs = [r for r in records if r["name"] == name]
        real = [r for r in evs if not r.get("cache_hit")]
        assert len(real) <= 1, (name, evs)  # at most warmup's compile
    # Steady state: iterations past the second compile NOTHING.
    assert counts[4] == counts[2], records
