"""Tier-1 wiring for fleetsan, the deterministic multi-process chaos
sanitizer (ISSUE 12).

Four layers, mirroring test_racesan.py one level up:

1. **Scheduler mechanics** — a seeded chaos schedule replays
   bit-identically (trace AND outcome), different seeds genuinely
   permute interleavings and fault placement.
2. **Reverted protocol bugs as runtime regressions** — the non-atomic
   writer (`writer="direct"`) is torn-read-detected on EVERY schedule,
   the shared-tempfile writer (`writer="shared_tmp"`) collides within a
   small seed sweep and replays from its recorded seed, and the
   no-per-peer-clock gateway consumer (`poller="naive"`) regresses the
   resident policy on every schedule.
3. **Mailbox hygiene units** — `read_params` tolerates torn/truncated/
   empty snapshot files (the PR 12 `BadZipFile`/`EOFError` fix) and
   `write_params`' pid-suffixed tmp names cannot collide across ranks.
4. **Fleet observability** — `FleetMonitor.snapshot()` fields, and the
   serving gateway's `/healthz` surfacing fleet membership + degrading
   to 503 when a peer's mailbox goes stale (ISSUE 12 satellite).

The chaos units drive the REAL `write_params`/`read_params`/
`FileMailboxWriter.poll_once`/`ParamMailbox`/`PolicyStore.swap` objects
on tiny trees — jax is imported transitively, no device work.
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from actor_critic_tpu.analysis import fleetsan
from actor_critic_tpu.analysis.fleetsan import FleetSanError
from actor_critic_tpu.parallel.multihost import (
    FleetMonitor,
    params_file,
    read_params,
    read_version,
    write_params,
)

# ---------------------------------------------------------------------------
# scheduler mechanics
# ---------------------------------------------------------------------------


def test_seeded_fleet_schedule_replays_bit_identically():
    reports = [
        fleetsan.exercise_fleet(seed=7, world=3, rounds=6) for _ in range(2)
    ]
    assert reports[0]["trace"] == reports[1]["trace"]
    assert reports[0]["faults"] == reports[1]["faults"]
    assert reports[0]["takes"] == reports[1]["takes"]
    assert reports[0]["recover_rounds"] == reports[1]["recover_rounds"]


def test_seeded_gateway_schedule_replays_bit_identically():
    reports = [fleetsan.exercise_gateway(seed=3) for _ in range(2)]
    assert reports[0]["trace"] == reports[1]["trace"]
    assert reports[0]["swaps"] == reports[1]["swaps"]
    assert reports[0]["faults"] == reports[1]["faults"]


def test_different_seeds_permute_schedules_and_faults():
    traces = set()
    fault_menus = set()
    for seed in range(8):
        r = fleetsan.exercise_fleet(seed=seed, world=3, rounds=5)
        traces.add(tuple(r["trace"]))
        fault_menus.add(tuple(r["faults"]))
    assert len(traces) > 1, "8 seeds produced one interleaving"
    assert len(fault_menus) > 1, "8 seeds produced one fault placement"


def test_clean_fleet_schedule_reports_progress():
    r = fleetsan.exercise_fleet(seed=0, world=3, rounds=8)
    assert r["violations"] == 0
    assert r["takes"] > 0 and r["deposits"] > 0
    # every injected kill recovered within the drain bound
    assert len(r["recover_rounds"]) == r["kills"]


# ---------------------------------------------------------------------------
# reverted protocol bugs reproduce deterministically
# ---------------------------------------------------------------------------


def test_direct_writer_torn_publish_caught_on_every_schedule():
    """The reverted non-atomic writer (consumed path written in place)
    is read torn at the interleave point — every seed, not a lucky
    preemption."""
    for seed in range(6):
        with pytest.raises(FleetSanError, match="unreadable|corrupt"):
            fleetsan.exercise_fleet(
                seed=seed, world=3, rounds=6, writer="direct", faults=False
            )


def test_shared_tmp_collision_caught_and_replays_from_its_seed():
    """The shared-tempfile writer collides within a small seed sweep;
    the recorded seed then reproduces the SAME detection bit-for-bit
    (racesan's catch-then-replay contract at process granularity)."""
    caught_seed = None
    first_msg = None
    for seed in range(16):
        try:
            fleetsan.exercise_fleet(
                seed=seed, world=3, rounds=8, writer="shared_tmp",
                faults=False,
            )
        except FleetSanError as e:
            caught_seed, first_msg = seed, str(e)
            break
    assert caught_seed is not None, (
        "16 seeds never collided the shared tempfile"
    )
    with pytest.raises(FleetSanError) as again:
        fleetsan.exercise_fleet(
            seed=caught_seed, world=3, rounds=8, writer="shared_tmp",
            faults=False,
        )
    assert str(again.value) == first_msg


def test_naive_gateway_poller_version_regression_every_schedule():
    """The reverted consumer (no per-peer clock, raw read-then-swap)
    swaps the replayed stale snapshot in — the scripted chaos sequence
    exercises the regression path on every schedule."""
    for seed in range(6):
        with pytest.raises(FleetSanError, match="regress|swapped BACK"):
            fleetsan.exercise_gateway(seed=seed, poller="naive")


def test_guarded_gateway_poller_sweeps_clean():
    for seed in range(6):
        r = fleetsan.exercise_gateway(seed=seed, poller="guarded")
        assert r["violations"] == 0
        assert r["swaps"] > 0


def test_seeded_replica_schedule_replays_bit_identically():
    reports = [
        fleetsan.exercise_replica_fleet(seed=5, versions=6, replicas=3)
        for _ in range(2)
    ]
    assert reports[0]["trace"] == reports[1]["trace"]
    assert reports[0]["swaps"] == reports[1]["swaps"]
    assert reports[0]["faults"] == reports[1]["faults"]


def test_replica_kill_mid_swap_schedules_sweep_clean():
    """ISSUE 17 leg b: the replica-kill-mid-swap scenario — the REAL
    MailboxPolicySyncer.poll_once into real PolicyStores under torn
    files, stale replays, and a seeded replica SIGKILL+cold-restart —
    never serves a torn policy, never regresses a version within one
    process lifetime, and every replica (including the rejoiner)
    converges to the final published version."""
    kills = 0
    for seed in range(6):
        r = fleetsan.exercise_replica_fleet(seed=seed, versions=6,
                                            replicas=3)
        assert r["violations"] == 0
        assert r["swaps"] > 0
        assert r["published"] == 6
        kills += r["kills"]
    assert kills > 0, "no schedule exercised the replica kill"


def test_quick_profile_sweeps_clean():
    """The exact fixed-seed profile scripts/tier1.sh runs (smaller
    schedule count here — the tier-1 step runs the full one)."""
    out = fleetsan.quick_profile(schedules=6, seed0=0)
    assert out["violations"] == 0
    assert out["schedules"] == 6
    assert out["fleet"]["takes"] > 0
    assert out["gateway"]["swaps"] > 0


# ---------------------------------------------------------------------------
# mailbox hygiene units (the PR 12 fixes as regressions)
# ---------------------------------------------------------------------------


def _tree():
    return {"w": np.zeros((2, 2), np.float32)}


def test_read_params_tolerates_truncated_and_empty_files(tmp_path):
    """`np.load` raises zipfile.BadZipFile on a truncated archive and
    EOFError on an empty one — neither is an OSError; the pre-fix
    reader died on the first torn snapshot."""
    mailbox = str(tmp_path)
    write_params(mailbox, 0, 3, _tree())
    path = params_file(mailbox, 0)
    size = os.path.getsize(path)
    for cut in (0, 1, size // 2, size - 1):
        with open(path, "r+b") as f:
            f.truncate(cut)
        assert read_params(mailbox, 0, _tree()) is None, (
            f"torn read at {cut}/{size} bytes was not tolerated"
        )
        assert read_version(mailbox, 0) is None
    # the next publish repairs the file for good
    write_params(mailbox, 0, 4, _tree())
    out = read_params(mailbox, 0, _tree())
    assert out is not None and out[0] == 4
    assert read_version(mailbox, 0) == 4


def test_write_params_tmp_names_are_process_unique(tmp_path):
    """The tmp is pid-suffixed next to the target: two ranks (or a
    restarted writer) publishing into a shared directory can never
    interleave into one tempfile."""
    mailbox = str(tmp_path)
    write_params(mailbox, 0, 1, _tree())
    write_params(mailbox, 1, 1, _tree())
    leftovers = [
        f
        for root, _dirs, files in os.walk(mailbox)
        for f in files
        if ".tmp" in f
    ]
    assert leftovers == [], f"stale tempfiles after publish: {leftovers}"


# ---------------------------------------------------------------------------
# fleet observability: FleetMonitor + gateway /healthz (satellite)
# ---------------------------------------------------------------------------


def test_fleet_monitor_snapshot_fields(tmp_path):
    mailbox = str(tmp_path)
    write_params(mailbox, 1, 5, _tree())
    write_params(mailbox, 2, 9, _tree())
    mon = FleetMonitor(mailbox, rank=0, world=3, stale_after_s=30.0)
    snap = mon.snapshot()
    assert snap["rank"] == 0 and snap["world"] == 3
    assert set(snap["peers"]) == {"1", "2"}
    assert snap["peers"]["1"]["version"] == 5
    assert snap["peers"]["2"]["version"] == 9
    assert snap["ok"] and snap["stale"] == []


def test_fleet_monitor_flags_silent_and_stale_peers(tmp_path):
    mailbox = str(tmp_path)
    write_params(mailbox, 1, 2, _tree())
    # peer 2 never published; peer 1 goes stale once its mtime ages out
    mon = FleetMonitor(mailbox, rank=0, world=3, stale_after_s=0.2)
    snap = mon.snapshot()
    assert 2 in snap["stale"] and not snap["ok"]
    assert snap["peers"]["1"]["published"]
    old = time.time() - 10.0
    os.utime(params_file(mailbox, 1), (old, old))
    snap = mon.snapshot()
    assert set(snap["stale"]) == {1, 2}


class _StubEngine:
    """jax-free engine: action = obs[:, 0] * params['scale'][0]."""

    max_rows = 8

    def prepare_params(self, params):
        return {k: np.array(v) for k, v in params.items()}

    def act(self, params, obs):
        return np.asarray(obs)[:, 0] * params["scale"][0]


def _get(url: str, timeout: float = 10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_gateway_healthz_surfaces_fleet_membership(tmp_path):
    """/healthz of a --distributed gateway carries rank/world/per-peer
    mailbox ages, and a stale peer degrades the endpoint to 503 — the
    LB fronting the fleet sees the partition, not just dead members."""
    from actor_critic_tpu import serving

    mailbox = str(tmp_path)
    write_params(mailbox, 1, 7, _tree())
    store = serving.PolicyStore()
    store.register("default", _StubEngine(), {"scale": np.ones(1)})
    fleet = FleetMonitor(mailbox, rank=0, world=2, stale_after_s=60.0)
    gw = serving.ServeGateway(store, port=0, fleet=fleet)
    try:
        status, body = _get(gw.url + "/healthz")
        assert status == 200
        assert body["fleet"]["rank"] == 0
        assert body["fleet"]["world"] == 2
        peer = body["fleet"]["peers"]["1"]
        assert peer["published"] and peer["version"] == 7
        assert peer["age_s"] is not None
        # the peer's mailbox ages past the bound -> fleet degraded
        old = time.time() - 3600.0
        os.utime(params_file(mailbox, 1), (old, old))
        status, body = _get(gw.url + "/healthz")
        assert status == 503
        assert body["status"] == "stalled"
        assert body["fleet"]["stale"] == [1]
    finally:
        gw.close()


def test_gateway_without_fleet_has_no_fleet_block():
    from actor_critic_tpu import serving

    store = serving.PolicyStore()
    store.register("default", _StubEngine(), {"scale": np.ones(1)})
    gw = serving.ServeGateway(store, port=0)
    try:
        status, body = _get(gw.url + "/healthz")
        assert status == 200
        assert "fleet" not in body
    finally:
        gw.close()


# ---------------------------------------------------------------------------
# the CLI contract tier-1 relies on
# ---------------------------------------------------------------------------


def _load_cli():
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "fleetsan_cli", os.path.join(repo, "scripts", "fleetsan.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cli_quick_profile_exits_zero(capsys):
    cli = _load_cli()
    assert cli.main(["--schedules", "4"]) == 0
    assert "clean" in capsys.readouterr().out


def test_cli_reverted_writer_exits_one(capsys):
    cli = _load_cli()
    assert cli.main(
        ["--scenario", "fleet", "--writer", "direct", "--schedules", "2"]
    ) == 1
    assert "VIOLATION" in capsys.readouterr().err


def test_cli_naive_poller_exits_one(capsys):
    cli = _load_cli()
    assert cli.main(
        ["--scenario", "gateway", "--poller", "naive", "--schedules", "2"]
    ) == 1
    assert "VIOLATION" in capsys.readouterr().err
