"""Env protocol tests: CartPole numerics vs installed gymnasium, auto-reset
semantics, vmap compatibility (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.envs import (
    make_bandit,
    make_cartpole,
    make_point_mass,
    make_two_state_mdp,
)


def test_cartpole_matches_gymnasium_dynamics():
    """Step both implementations from identical states with identical
    action sequences; trajectories must match to float32 precision."""
    gym = pytest.importorskip("gymnasium")
    genv = gym.make("CartPole-v1").unwrapped
    jenv = make_cartpole()

    state, obs = jenv.reset(jax.random.key(0))
    genv.reset(seed=0)
    # Force identical initial state.
    genv.state = np.asarray(obs, dtype=np.float64)

    rng = np.random.RandomState(42)
    for t in range(50):
        action = int(rng.randint(2))
        out = jenv.step(state, jnp.asarray(action))
        gobs, grew, gterm, gtrunc, _ = genv.step(action)
        if gterm:
            # JAX env auto-resets; compare the pre-reset obs instead.
            np.testing.assert_allclose(
                out.info["final_obs"], gobs, rtol=1e-5, atol=1e-5
            )
            assert float(out.done) == 1.0
            break
        np.testing.assert_allclose(out.obs, gobs, rtol=1e-5, atol=1e-5)
        assert float(out.reward) == grew == 1.0
        state = out.state


def test_cartpole_truncates_at_500():
    """The TimeLimit must truncate (not terminate) at step 500."""
    env = make_cartpole()
    state, obs = env.reset(jax.random.key(1))
    # Check the step-counter logic directly: craft a state at t=499.
    state = state._replace(t=jnp.asarray(499, jnp.int32))
    out = env.step(state, jnp.asarray(0))
    term = float(out.info["terminated"])
    assert float(out.done) == 1.0
    # near-origin state: must be truncation, not termination
    assert term == 0.0
    # auto-reset: new episode's t is 0
    assert int(out.state.t) == 0


def test_auto_reset_gives_fresh_obs():
    env = make_two_state_mdp(horizon=3)
    state, obs = env.reset(jax.random.key(0))
    for _ in range(2):
        out = env.step(state, jnp.asarray(1))
        state = out.state
        assert float(out.done) == 0.0
    out = env.step(state, jnp.asarray(1))
    assert float(out.done) == 1.0
    # reward for the final step is still granted
    assert float(out.reward) == 1.0
    # final_obs reflects the pre-reset transition (state 1 one-hot)
    np.testing.assert_allclose(out.info["final_obs"], [0.0, 1.0])
    # post-reset t is 0 and episode continues
    assert int(out.state.t) == 0


def test_bandit_one_step_episodes():
    env = make_bandit((0.1, 0.9))
    state, obs = env.reset(jax.random.key(0))
    out = env.step(state, jnp.asarray(1))
    assert float(out.reward) == pytest.approx(0.9)
    assert float(out.done) == 1.0
    out2 = env.step(out.state, jnp.asarray(0))
    assert float(out2.reward) == pytest.approx(0.1)
    assert float(out2.done) == 1.0


def test_point_mass_reward_and_clip():
    env = make_point_mass()
    state, obs = env.reset(jax.random.key(3))
    pos = float(obs[0])
    out = env.step(state, jnp.asarray([5.0]))  # clipped to 1.0
    assert float(out.reward) == pytest.approx(-((pos + 1.0) ** 2), rel=1e-5)


def test_envs_vmap_and_jit():
    """The whole protocol must survive vmap+jit (the rollout shape)."""
    env = make_cartpole()
    E = 8
    keys = jax.random.split(jax.random.key(0), E)
    state, obs = jax.vmap(env.reset)(keys)
    assert obs.shape == (E, 4)

    @jax.jit
    def step_all(state, actions):
        return jax.vmap(env.step)(state, actions)

    out = step_all(state, jnp.ones((E,), jnp.int32))
    assert out.obs.shape == (E, 4)
    assert out.reward.shape == (E,)
    out2 = step_all(out.state, jnp.zeros((E,), jnp.int32))
    assert bool(jnp.all(jnp.isfinite(out2.obs)))


def test_pendulum_matches_gymnasium_dynamics():
    """Pure-JAX Pendulum vs installed gymnasium from identical states and
    torque sequences (raw-torque mode so actions compare 1:1): obs and
    reward must match to float32 precision over a full 200-step episode."""
    gym = pytest.importorskip("gymnasium")
    from actor_critic_tpu.envs import make_pendulum

    genv = gym.make("Pendulum-v1").unwrapped
    jenv = make_pendulum(scale_actions=False)

    state, _ = jenv.reset(jax.random.key(3))
    rng = np.random.RandomState(7)
    th, thdot = rng.uniform(-np.pi, np.pi), rng.uniform(-1, 1)
    genv.reset(seed=0)
    genv.state = np.array([th, thdot], np.float64)
    state = state._replace(
        theta=jnp.asarray(th, jnp.float32),
        theta_dot=jnp.asarray(thdot, jnp.float32),
    )

    for t in range(199):
        a = rng.uniform(-2.5, 2.5)  # out-of-range exercises the clip
        out = jenv.step(state, jnp.asarray([a], jnp.float32))
        gobs, grew, _, _, _ = genv.step(np.array([a], np.float32))
        np.testing.assert_allclose(out.obs, gobs, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(float(out.reward), grew, rtol=1e-4, atol=1e-4)
        assert float(out.done) == 0.0
        state = out.state


def test_pendulum_scaled_actions_and_truncation():
    """Default scale_actions=True: normalized action a executes as torque
    2a (a=1 ≡ raw torque 2.0); episodes truncate (never terminate) at 200."""
    from actor_critic_tpu.envs import make_pendulum

    scaled = make_pendulum()
    raw = make_pendulum(scale_actions=False)
    s1, _ = scaled.reset(jax.random.key(5))
    s2, _ = raw.reset(jax.random.key(5))  # same key → same start
    o1 = scaled.step(s1, jnp.asarray([0.75], jnp.float32))
    o2 = raw.step(s2, jnp.asarray([1.5], jnp.float32))
    np.testing.assert_allclose(o1.obs, o2.obs, rtol=1e-6)
    np.testing.assert_allclose(float(o1.reward), float(o2.reward), rtol=1e-6)

    st = s1._replace(t=jnp.asarray(199, jnp.int32))
    out = scaled.step(st, jnp.asarray([0.0], jnp.float32))
    assert float(out.done) == 1.0
    assert float(out.info["terminated"]) == 0.0  # truncation, not termination
    assert int(out.state.t) == 0


def test_acrobot_matches_gymnasium_dynamics():
    """Gymnasium-parity for the Acrobot member (ISSUE 11 satellite,
    same discipline as cartpole/pendulum): re-sync both implementations
    to the same state each step — the double pendulum is chaotic, so
    per-step comparison tests the RK4 dynamics themselves rather than
    float32 drift amplification — and compare obs/reward/termination."""
    gym = pytest.importorskip("gymnasium")
    from actor_critic_tpu.envs import make_acrobot

    genv = gym.make("Acrobot-v1").unwrapped
    jenv = make_acrobot()
    genv.reset(seed=0)
    state, _ = jenv.reset(jax.random.key(0))

    rng = np.random.RandomState(11)
    s = rng.uniform(-0.5, 0.5, size=4)
    for t in range(40):
        action = int(rng.randint(3))
        genv.state = s.astype(np.float64).copy()
        jstate = state._replace(
            theta1=jnp.asarray(s[0], jnp.float32),
            theta2=jnp.asarray(s[1], jnp.float32),
            dtheta1=jnp.asarray(s[2], jnp.float32),
            dtheta2=jnp.asarray(s[3], jnp.float32),
        )
        out = jenv.step(jstate, jnp.asarray(action))
        gobs, grew, gterm, _, _ = genv.step(action)
        if gterm:
            # The JAX env auto-resets; the pre-reset obs must match.
            np.testing.assert_allclose(
                out.info["final_obs"], gobs, rtol=1e-4, atol=1e-4
            )
            assert float(out.info["terminated"]) == 1.0
            assert float(out.reward) == grew == 0.0
        else:
            np.testing.assert_allclose(out.obs, gobs, rtol=1e-4, atol=1e-4)
            assert float(out.reward) == grew == -1.0
            assert float(out.done) == 0.0
        # Continue from gymnasium's float64 state (the reference).
        s = np.asarray(genv.state, np.float64)
        state = out.state


def test_acrobot_defaults_and_truncation():
    """Default scenario carries gymnasium's exact constants (unrandomized
    dynamics); velocities clip at 4π/9π; the TimeLimit truncates (not
    terminates) at 500."""
    from actor_critic_tpu.envs import acrobot as ab
    from actor_critic_tpu.envs import make_acrobot

    env = make_acrobot()
    state, obs = env.reset(jax.random.key(1))
    sc = state.scenario
    assert float(sc.gravity) == np.float32(ab.GRAVITY)
    assert float(sc.link_mass_1) == np.float32(ab.LINK_MASS_1)
    assert float(sc.link_length_2) == np.float32(ab.LINK_LENGTH_2)
    assert float(sc.torque) == np.float32(ab.TORQUE)
    assert obs.shape == (6,)
    # Reset distribution: uniform(-0.1, 0.1) on all four state vars.
    assert abs(float(state.theta1)) <= 0.1 and abs(float(state.dtheta2)) <= 0.1

    st = state._replace(
        dtheta1=jnp.asarray(100.0, jnp.float32),
        dtheta2=jnp.asarray(-100.0, jnp.float32),
    )
    out = env.step(st, jnp.asarray(1))
    assert abs(float(out.state.dtheta1)) <= float(ab.MAX_VEL_1) + 1e-5
    assert abs(float(out.state.dtheta2)) <= float(ab.MAX_VEL_2) + 1e-5

    st = state._replace(t=jnp.asarray(499, jnp.int32))
    out = env.step(st, jnp.asarray(1))
    assert float(out.done) == 1.0
    assert float(out.info["terminated"]) == 0.0  # hanging start: truncation
    assert int(out.state.t) == 0  # auto-reset


def test_acrobot_scenario_fleet():
    """randomize=r draws per-instance physics reproducibly (the ISSUE 8
    contract extended to the new member)."""
    from actor_critic_tpu.envs import make_acrobot

    env = make_acrobot(randomize=0.3)
    keys = jax.random.split(jax.random.key(2), 64)
    s1, _ = jax.vmap(env.reset)(keys)
    s2, _ = jax.vmap(env.reset)(keys)
    m = np.asarray(s1.scenario.link_mass_2)
    assert len(np.unique(m)) > 32
    assert (m >= 1.0 * 0.7 - 1e-6).all() and (m <= 1.0 * 1.3 + 1e-6).all()
    np.testing.assert_array_equal(m, np.asarray(s2.scenario.link_mass_2))


def test_maze_procedural_generation_and_mechanics():
    """The maze member (ISSUE 11): per-episode procedural layouts from
    the instance's own PRNG stream, wall/obstacle blocking, goal
    termination with reward, time-limit truncation."""
    from actor_critic_tpu.envs import make_maze

    env = make_maze(size=6)
    state, obs = env.reset(jax.random.key(0))
    assert obs.shape == (13,)
    grid = np.asarray(state.grid)
    assert grid.shape == (6, 6) and set(np.unique(grid)) <= {0.0, 1.0}
    # Start and goal cells are always free and distinct.
    assert grid[int(state.row), int(state.col)] == 0.0
    assert grid[int(state.goal_row), int(state.goal_col)] == 0.0
    assert (int(state.row), int(state.col)) != (
        int(state.goal_row), int(state.goal_col)
    )

    # Walking into the arena wall stays in place and pays the step cost
    # (goal pinned far away so the forced position can't terminate).
    st = state._replace(
        row=jnp.asarray(0, jnp.int32), col=jnp.asarray(0, jnp.int32),
        goal_row=jnp.asarray(4, jnp.int32), goal_col=jnp.asarray(4, jnp.int32),
        grid=state.grid.at[0, 0].set(0.0),
    )
    out = env.step(st, jnp.asarray(0))  # up, off the top edge
    assert int(out.state.row) == 0 and int(out.state.col) == 0
    assert float(out.done) == 0.0
    assert float(out.reward) == pytest.approx(-0.05)

    # Stepping onto the goal terminates with goal_reward - step_cost.
    st = state._replace(
        row=jnp.asarray(2, jnp.int32), col=jnp.asarray(2, jnp.int32),
        goal_row=jnp.asarray(2, jnp.int32), goal_col=jnp.asarray(3, jnp.int32),
        grid=state.grid.at[2, 3].set(0.0),
    )
    out = env.step(st, jnp.asarray(1))  # right, onto the goal
    assert float(out.info["terminated"]) == 1.0
    assert float(out.done) == 1.0
    assert float(out.reward) == pytest.approx(1.0 - 0.05)

    # An obstacle blocks the same move.
    blocked = st._replace(grid=st.grid.at[2, 3].set(1.0))
    out = env.step(blocked, jnp.asarray(1))
    assert int(out.state.row) == 2 and int(out.state.col) == 2
    assert float(out.done) == 0.0

    # Truncation at 8*size; auto-reset regenerates a DIFFERENT layout.
    st = state._replace(t=jnp.asarray(8 * 6 - 1, jnp.int32))
    out = env.step(st, jnp.asarray(0))
    assert float(out.done) == 1.0
    assert float(out.info["terminated"]) in (0.0, 1.0)
    assert int(out.state.t) == 0
    assert not np.array_equal(np.asarray(out.state.grid), grid)


def test_maze_fleet_reproducible():
    from actor_critic_tpu.envs import make_maze

    env = make_maze(randomize=0.4)
    keys = jax.random.split(jax.random.key(3), 32)
    s1, o1 = jax.vmap(env.reset)(keys)
    s2, o2 = jax.vmap(env.reset)(keys)
    np.testing.assert_array_equal(np.asarray(s1.grid), np.asarray(s2.grid))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    d = np.asarray(s1.scenario.density)
    assert len(np.unique(d)) > 16  # per-instance generation params
