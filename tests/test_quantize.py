"""Quantized replay tests (ISSUE 8): per-codec round-trip error bounds,
capacity accounting (the ≥3x mixed-mode acceptance number), ring-level
encode/decode through wraparound and donation, quantizer stats riding
the checkpoint save tree (fused restore-then-continue bitwise), and
DDPG/TD3/SAC eval-return parity fp32 vs mixed at the same seed."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu import replay
from actor_critic_tpu.algos.common import OffPolicyTransition
from actor_critic_tpu.replay import quantize


def _transition_example(obs_dim=3, act_dim=1):
    return OffPolicyTransition(
        obs=jnp.zeros((obs_dim,), jnp.float32),
        action=jnp.zeros((act_dim,), jnp.float32),
        reward=jnp.zeros((), jnp.float32),
        next_obs=jnp.zeros((obs_dim,), jnp.float32),
        terminated=jnp.zeros((), jnp.float32),
        done=jnp.zeros((), jnp.float32),
    )


def _transition_batch(n, obs_dim=3, act_dim=1, seed=0):
    rng = np.random.default_rng(seed)
    return OffPolicyTransition(
        obs=jnp.asarray(rng.normal(1.5, 2.0, (n, obs_dim)), jnp.float32),
        action=jnp.asarray(
            np.tanh(rng.normal(size=(n, act_dim))), jnp.float32
        ),
        reward=jnp.asarray(rng.normal(-2.0, 3.0, (n,)), jnp.float32),
        next_obs=jnp.asarray(rng.normal(1.5, 2.0, (n, obs_dim)), jnp.float32),
        terminated=jnp.asarray(rng.random(n) < 0.1, jnp.float32),
        done=jnp.asarray(rng.random(n) < 0.15, jnp.float32),
    )


class TestCodecRoundTrip:
    """decode(encode(x)) error bounds per codec vs fp32 ground truth."""

    def _roundtrip(self, kind, x, stats=None):
        if stats is None:
            stats = quantize.init_stats(kind, x[0])
            stats = quantize.update_stats(kind, stats, x)
        q = quantize.encode(kind, stats, x, quantize.storage_dtype(kind, x.dtype))
        return np.asarray(quantize.decode(kind, stats, q)), stats

    def test_raw_exact(self):
        x = jnp.asarray(np.random.default_rng(0).normal(size=(64, 3)), jnp.float32)
        out, _ = self._roundtrip("raw", x)
        np.testing.assert_array_equal(out, np.asarray(x))

    def test_bool8_exact(self):
        x = jnp.asarray(np.random.default_rng(1).random((256,)) < 0.5, jnp.float32)
        out, _ = self._roundtrip("bool8", x)
        np.testing.assert_array_equal(out, np.asarray(x))

    def test_f16_relative_bound(self):
        x = jnp.asarray(
            np.random.default_rng(2).normal(0, 10, (512,)), jnp.float32
        )
        out, _ = self._roundtrip("f16", x)
        np.testing.assert_allclose(out, np.asarray(x), rtol=2**-10)

    def test_i8_unit_bound(self):
        x = jnp.asarray(
            np.random.default_rng(3).uniform(-1, 1, (512,)), jnp.float32
        )
        out, _ = self._roundtrip("i8_unit", x)
        assert np.abs(out - np.asarray(x)).max() <= 1.0 / 127.0
        # And the bound is exactly the quantization step: the codec must
        # not silently rescale inside [-1, 1].
        assert np.abs(out).max() <= 1.0

    def test_i8_standardized_bound(self):
        """Error <= scale/127 per element for in-range data, with
        per-FEATURE stats (each column standardized by its own range)."""
        rng = np.random.default_rng(4)
        x = jnp.asarray(
            np.stack(
                [rng.normal(100.0, 1.0, 1024), rng.normal(-3.0, 30.0, 1024)],
                axis=-1,
            ),
            jnp.float32,
        )
        out, stats = self._roundtrip("i8", x)
        step = np.asarray(stats.scale) / 127.0  # per-feature
        err = np.abs(out - np.asarray(x))
        assert (err <= step + 1e-5).all(), (err.max(0), step)
        # Feature 0 (tight range around 100) must quantize ~30x finer
        # than feature 1 (wide range) — the point of per-feature stats.
        assert step[0] < step[1] / 10.0

    def test_i8_out_of_range_clips(self):
        x = jnp.asarray([0.0, 1.0, -1.0, 50.0], jnp.float32)
        stats = quantize.QuantStats(
            mean=jnp.zeros(()), scale=jnp.ones(()), count=jnp.ones((), jnp.int32)
        )
        out, _ = self._roundtrip("i8", x, stats)
        np.testing.assert_allclose(out[-1], 1.0, atol=1e-6)  # clipped to scale

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError, match="unknown codec"):
            quantize.storage_dtype("f8", jnp.float32)
        with pytest.raises(ValueError, match="replay_dtype"):
            quantize.offpolicy_codecs("bf16")


class TestStats:
    def test_scale_monotone_mean_tracks(self):
        """scale never shrinks (old entries always decode in-range);
        mean converges to the data mean via cumulative averaging."""
        stats = quantize.init_stats("i8", jnp.zeros(()))
        rng = np.random.default_rng(5)
        scales = []
        for i in range(20):
            batch = jnp.asarray(rng.normal(7.0, 2.0, (256,)), jnp.float32)
            stats = quantize.update_stats("i8", stats, batch)
            scales.append(float(stats.scale))
        assert all(b >= a for a, b in zip(scales, scales[1:]))
        assert abs(float(stats.mean) - 7.0) < 0.2
        assert int(stats.count) == 20 * 256

    def test_stats_freeze_after_calibration(self):
        """Past CALIBRATION_TRANSITIONS the stats must STOP moving, even
        under a shifted data distribution — the drift-free-decode
        guarantee for post-calibration ring entries (a drifting mean
        re-biases every old entry by the full drift; measured to cost
        DDPG ~2.7 return on point_mass before the freeze)."""
        stats = quantize.init_stats("i8", jnp.zeros(()))
        rng = np.random.default_rng(6)
        b = quantize.CALIBRATION_TRANSITIONS  # one batch = whole window
        stats = quantize.update_stats(
            "i8", stats, jnp.asarray(rng.normal(0.0, 1.0, (b,)), jnp.float32)
        )
        frozen_mean, frozen_scale = float(stats.mean), float(stats.scale)
        stats = quantize.update_stats(
            "i8", stats, jnp.asarray(rng.normal(50.0, 9.0, (b,)), jnp.float32)
        )
        assert float(stats.mean) == frozen_mean
        assert float(stats.scale) == frozen_scale
        assert int(stats.count) == 2 * b  # count still tallies

    def test_stat_free_codecs_untouched(self):
        stats = quantize.init_stats("f16", jnp.zeros((3,)))
        out = quantize.update_stats(
            "f16", stats, jnp.ones((8, 3), jnp.float32)
        )
        assert out is stats  # literally a no-op


class TestCapacityAccounting:
    def test_mixed_mode_hits_3x(self):
        """ISSUE 8 acceptance: mixed-precision replay stores >=3x
        transitions per HBM byte vs fp32 at the Pendulum transition
        shape (obs 3, action 1)."""
        codecs = quantize.offpolicy_codecs("mixed")
        state = replay.init(_transition_example(), 128, codecs)
        rep = quantize.capacity_report(state, codecs)
        assert rep["fp32_bytes_per_transition"] == 40
        assert rep["bytes_per_transition"] == 13
        assert rep["capacity_multiplier"] >= 3.0
        assert "action:raw" in rep["codec_mix"]  # actions stay fp32

    def test_int8_mode_hits_4x(self):
        codecs = quantize.offpolicy_codecs("int8")
        state = replay.init(_transition_example(), 128, codecs)
        rep = quantize.capacity_report(state, codecs)
        assert rep["capacity_multiplier"] >= 4.0

    def test_fp32_mode_is_identity(self):
        codecs = quantize.offpolicy_codecs("fp32")
        state = replay.init(_transition_example(), 128, codecs)
        rep = quantize.capacity_report(state, codecs)
        assert rep["capacity_multiplier"] == 1.0
        assert state.storage.obs.dtype == jnp.float32


class TestQuantizedRing:
    def test_add_sample_roundtrip_within_bounds(self):
        codecs = quantize.offpolicy_codecs("mixed")
        state = replay.init(_transition_example(), 256, codecs)
        batch = _transition_batch(128)
        state = replay.add_batch(state, batch, codecs)
        assert state.storage.obs.dtype == jnp.int8
        assert state.storage.done.dtype == jnp.int8
        out = replay.sample(state, jax.random.key(0), 512, codecs)
        # Decoded samples stay float32 and inside the encoded range.
        assert out.obs.dtype == jnp.float32
        step = np.asarray(state.quant.obs.scale) / 127.0
        src = np.asarray(batch.obs)
        lo = src.min(0) - step - 1e-5
        hi = src.max(0) + step + 1e-5
        o = np.asarray(out.obs)
        assert (o >= lo).all() and (o <= hi).all()
        # Flags decode exactly.
        assert set(np.unique(np.asarray(out.done))) <= {0.0, 1.0}
        # Actions pass through untouched in mixed mode.
        assert state.storage.action.dtype == jnp.float32

    def test_wraparound_preserves_newest(self):
        """The quantized ring keeps the same wrap semantics as fp32:
        reward values survive (within codec error) across the seam."""
        codecs = quantize.offpolicy_codecs("mixed")
        state = replay.init(_transition_example(), 8, codecs)
        for start in range(0, 16, 4):
            vals = np.arange(start, start + 4, dtype=np.float32)
            b = _transition_batch(4, seed=start)._replace(
                reward=jnp.asarray(vals)
            )
            state = replay.add_batch(state, b, codecs)
        assert int(state.size) == 8
        dec = np.asarray(
            replay.sample(state, jax.random.key(1), 256, codecs).reward
        )
        step = float(state.quant.reward.scale) / 127.0
        # Only the newest 8 rewards (8..15) are sampleable.
        assert dec.min() >= 8.0 - step - 1e-5
        assert dec.max() <= 15.0 + step + 1e-5

    def test_sample_sequences_decodes(self):
        codecs = quantize.offpolicy_codecs("mixed")
        state = replay.init(_transition_example(), 64, codecs)
        vals = np.arange(40, dtype=np.float32)
        b = _transition_batch(40)._replace(reward=jnp.asarray(vals))
        state = replay.add_batch(state, b, codecs)
        out = replay.sample_sequences(state, jax.random.key(2), 16, 5, codecs)
        r = np.asarray(out.reward)
        assert r.shape == (16, 5) and r.dtype == np.float32
        # Consecutive inserts stay consecutive after decode (within the
        # reward codec's step).
        step = float(state.quant.reward.scale) / 127.0
        assert np.abs(np.diff(r, axis=1) - 1.0).max() <= 2 * step + 1e-5

    def test_defaulted_codecs_on_quantized_ring_refused(self):
        """sample/add_batch without a codec spec against a quantized
        ring must raise, not silently hand back raw int8 codes (a
        ~127x-scaled garbage batch with no dtype error anywhere)."""
        codecs = quantize.offpolicy_codecs("mixed")
        state = replay.init(_transition_example(), 64, codecs)
        state = replay.add_batch(state, _transition_batch(8), codecs)
        with pytest.raises(ValueError, match="quantized storage"):
            replay.sample(state, jax.random.key(0), 4)
        with pytest.raises(ValueError, match="quantized storage"):
            replay.sample_sequences(state, jax.random.key(0), 4, 2)
        with pytest.raises(ValueError, match="quantized storage"):
            replay.add_batch(state, _transition_batch(8))
        # Explicit codecs keep working, and fp32 rings keep the old
        # no-codecs call shape.
        replay.sample(state, jax.random.key(0), 4, codecs)
        fp32 = replay.init(_transition_example(), 64)
        fp32 = replay.add_batch(fp32, _transition_batch(8))
        replay.sample(fp32, jax.random.key(0), 4)

    def test_small_magnitude_leaf_keeps_resolution(self):
        """The scale seed must not floor the quantization step: a leaf
        whose values live at ~0.05 magnitude must round-trip with error
        bounded by ITS OWN range, not by a fixed 1/127 step."""
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.uniform(-0.05, 0.05, (2048,)), jnp.float32)
        stats = quantize.update_stats(
            "i8", quantize.init_stats("i8", x[0]), x
        )
        q = quantize.encode("i8", stats, x, jnp.int8)
        out = np.asarray(quantize.decode("i8", stats, q))
        assert float(stats.scale) < 0.2
        assert np.abs(out - np.asarray(x)).max() <= float(stats.scale) / 127

    def test_inplace_update_under_donation(self):
        """The donated jitted add must reuse the int8 storage buffer —
        the codec wrappers must not break the in-place scatter."""
        codecs = quantize.offpolicy_codecs("mixed")
        state = replay.init(_transition_example(), 1024, codecs)
        add = jax.jit(
            lambda s, b: replay.add_batch(s, b, codecs), donate_argnums=0
        )
        state = add(state, _transition_batch(4, seed=0))  # compile
        before = state.storage.obs.unsafe_buffer_pointer()
        state = add(state, _transition_batch(4, seed=1))
        jax.block_until_ready(state)
        after = state.storage.obs.unsafe_buffer_pointer()
        if before != after:
            pytest.skip("platform did not honor donation")
        assert int(state.size) == 8


# ---------------------------------------------------------------------------
# Quantizer stats ride the save tree: fused restore-then-continue bitwise
# ---------------------------------------------------------------------------


def _tiny_mixed_ddpg():
    from actor_critic_tpu.algos import ddpg
    from actor_critic_tpu.envs import make_point_mass

    env = make_point_mass()
    cfg = ddpg.DDPGConfig(
        num_envs=8, steps_per_iter=4, updates_per_iter=2,
        buffer_capacity=512, batch_size=32, hidden=(16,),
        warmup_steps=16, replay_dtype="mixed",
    )
    state = ddpg.init_state(env, cfg, jax.random.key(0))
    step = jax.jit(ddpg.make_train_step(env, cfg))
    return state, step


def test_fused_mixed_resume_bitwise(tmp_path):
    """Save a quantized-replay DDPG state mid-run, restore into a fresh
    template, continue — bitwise equal to the uninterrupted run. This is
    the proof the QuantStats (mean/scale/count) ride the save tree: a
    restore that dropped or re-zeroed them would decode every sampled
    batch through a different affine map and diverge immediately."""
    from actor_critic_tpu.utils.checkpoint import Checkpointer

    state0, step = _tiny_mixed_ddpg()

    full = state0
    for _ in range(4):
        full, _ = step(full)

    half = state0
    for _ in range(2):
        half, _ = step(half)
    with Checkpointer(tmp_path / "ck") as ck:
        jax.block_until_ready(half)
        ck.save(2, half, force=True)
        ck.wait()
        fresh, _ = _tiny_mixed_ddpg()
        resumed = ck.restore(fresh, 2)
    # The restored stats must be LIVE values, not the template's zeros.
    assert float(resumed.learner.replay.quant.obs.count) > 0
    for _ in range(2):
        resumed, _ = step(resumed)

    la, lb = jax.tree.leaves(full), jax.tree.leaves(resumed)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Telemetry: the host loop's replay gauge and run_report's Resources row
# ---------------------------------------------------------------------------


def test_host_loop_registers_replay_gauge():
    """off_policy_train_host registers a 'replay' sampler gauge while it
    runs (capacity/bytes-per-transition/codec mix — the run_report
    Resources row's source) and unregisters it on exit."""
    import dataclasses

    pytest.importorskip("gymnasium")
    from actor_critic_tpu.algos import ddpg
    from actor_critic_tpu.envs.host_pool import HostEnvPool
    from actor_critic_tpu.telemetry import sampler

    cfg = ddpg.DDPGConfig(
        num_envs=2, steps_per_iter=4, updates_per_iter=1,
        buffer_capacity=256, batch_size=8, warmup_steps=8, hidden=(16,),
        replay_dtype="mixed",
    )
    seen: dict = {}

    def log_fn(it, m):
        row = sampler.sample_row()
        if isinstance(row.get("replay"), dict):
            seen.update(row["replay"])

    pool = HostEnvPool(
        "Pendulum-v1", num_envs=2, seed=0,
        normalize_obs=False, normalize_reward=False,
    )
    try:
        ddpg.train_host(
            pool, cfg, num_iterations=2, seed=0, log_every=1, log_fn=log_fn
        )
    finally:
        pool.close()
    assert seen.get("capacity") == 256
    assert seen.get("mode") == "mixed"
    assert seen.get("bytes_per_transition") == 13
    assert seen.get("capacity_multiplier") >= 3.0
    # Unregistered after the loop returns.
    assert "replay" not in sampler.sample_row()
    # fields() sanity so a config rename can't silently skip this test.
    assert any(
        f.name == "replay_dtype" for f in dataclasses.fields(cfg)
    )


def test_run_report_renders_replay_row(tmp_path):
    import importlib.util
    import json
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "run_report",
        Path(__file__).parent.parent / "scripts" / "run_report.py",
    )
    run_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(run_report)

    rows = [
        {"ts": 1.0, "recompiles": 0,
         "replay": {"capacity": 65536, "bytes_per_transition": 13,
                    "fp32_bytes_per_transition": 40,
                    "capacity_multiplier": 3.08, "ring_bytes": 851968,
                    "codec_mix": "obs:i8,action:raw,reward:i8",
                    "mode": "mixed"}},
    ]
    text = "\n".join(run_report.resource_summary(rows))
    assert "replay ring" in text
    assert "65536 slots x 13 B/transition" in text
    assert "3.08x transitions/byte" in text
    assert "mode mixed" in text

    (tmp_path / "resources.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in rows)
    )
    report = run_report.render(str(tmp_path))
    assert "replay ring" in report


# ---------------------------------------------------------------------------
# fp32 vs mixed eval-return parity, same seed (tolerance-gated)
# ---------------------------------------------------------------------------


def _eval_offpolicy(env, cfg, state, algo_mod):
    from actor_critic_tpu.algos.common import evaluate

    actor, _ = algo_mod._modules(env.spec.action_dim, cfg)
    if hasattr(actor, "apply") and type(actor).__name__ == "SquashedGaussianActor":
        act = lambda p, o: actor.apply(p, o).mode()  # noqa: E731
    else:
        act = actor.apply
    return float(
        evaluate(
            env, act, state.learner.actor_params, jax.random.key(99),
            num_envs=32, num_steps=16,
        )
    )


@pytest.mark.parametrize("algo", ["ddpg", "td3", "sac"])
def test_eval_parity_fp32_vs_mixed(algo):
    """ISSUE 8 acceptance: same-seed short runs in fp32 and mixed modes
    both learn point_mass (optimal 0, random ~-6) and land within a
    tolerance of each other — the quantization must not change what the
    policy converges to, only the bytes it trains from."""
    from actor_critic_tpu.algos import ddpg, sac
    from actor_critic_tpu.envs import make_point_mass

    env = make_point_mass()
    results = {}
    # Configs/seeds mirror the proven single-mode learning tests in
    # test_ddpg.py / test_sac.py — the fp32 leg IS that test, so a
    # parity failure isolates the codec, not the tuning.
    for mode in ("fp32", "mixed"):
        if algo == "sac":
            cfg = sac.SACConfig(
                num_envs=16, steps_per_iter=4, updates_per_iter=4,
                buffer_capacity=32768, batch_size=64, hidden=(32, 32),
                actor_lr=1e-3, critic_lr=1e-3, alpha_lr=1e-3,
                warmup_steps=256, replay_dtype=mode,
            )
            state, _ = sac.train(env, cfg, num_iterations=250, seed=0)
            results[mode] = _eval_offpolicy(env, cfg, state, sac)
        else:
            kw = dict(
                num_envs=16, steps_per_iter=4, updates_per_iter=4,
                buffer_capacity=32768, batch_size=64, hidden=(32, 32),
                actor_lr=1e-3, critic_lr=1e-3, warmup_steps=256,
                exploration_noise=0.2, replay_dtype=mode,
            )
            seed = 2 if algo == "td3" else 1
            cfg = (
                ddpg.td3_config(**kw) if algo == "td3"
                else ddpg.DDPGConfig(**kw)
            )
            state, _ = ddpg.train(env, cfg, num_iterations=250, seed=seed)
            results[mode] = _eval_offpolicy(env, cfg, state, ddpg)
    assert results["fp32"] > -1.0, results
    assert results["mixed"] > -1.0, results
    assert abs(results["fp32"] - results["mixed"]) < 1.0, results
