"""SLO histogram layer (telemetry/histo.py, ISSUE 16): fixed-boundary
cumulative histograms whose snapshots merge EXACTLY across processes
(fleet bucket k == sum of rank bucket k — the property point
percentiles lack), quantiles recovered by linear interpolation inside
the landing bucket, and a Prometheus render/parse round-trip the fleet
aggregator's scrape decoder rides."""

import math

import pytest

from actor_critic_tpu.telemetry import histo


def test_boundaries_must_be_strictly_increasing():
    with pytest.raises(ValueError):
        histo.Histogram(())
    with pytest.raises(ValueError):
        histo.Histogram((1.0, 1.0, 2.0))
    with pytest.raises(ValueError):
        histo.Histogram((5.0, 1.0))


def test_snapshot_buckets_are_cumulative_and_inf_equals_count():
    h = histo.Histogram((1.0, 10.0, 100.0))
    for v in (0.5, 0.7, 5.0, 50.0, 5000.0):
        h.observe(v)
    snap = h.snapshot()
    assert histo.is_snapshot(snap)
    # cumulative: <=1 -> 2, <=10 -> 3, <=100 -> 4, +Inf -> 5
    assert snap["buckets"] == [2, 3, 4, 5]
    assert snap["count"] == 5 == snap["buckets"][-1]
    assert snap["sum"] == pytest.approx(5056.2)


def test_observe_many_matches_singles_and_skips_nan():
    a = histo.Histogram((1.0, 2.0))
    b = histo.Histogram((1.0, 2.0))
    vals = [0.5, 1.5, 3.0, 0.1]
    for v in vals:
        a.observe(v)
    a.observe(float("nan"))
    b.observe_many(vals + [float("nan")])
    assert a.snapshot()["buckets"] == b.snapshot()["buckets"]
    assert a.snapshot()["count"] == len(vals)
    assert not math.isnan(a.snapshot()["sum"])


def test_merge_is_exact_bucketwise_addition():
    a = histo.Histogram((1.0, 10.0))
    b = histo.Histogram((1.0, 10.0))
    a.observe_many([0.5, 5.0, 50.0])
    b.observe_many([0.1, 0.2, 7.0])
    sa, sb = a.snapshot(), b.snapshot()
    m = histo.merge([sa, sb])
    assert m["buckets"] == [
        x + y for x, y in zip(sa["buckets"], sb["buckets"])
    ]
    assert m["count"] == sa["count"] + sb["count"]
    assert m["sum"] == pytest.approx(sa["sum"] + sb["sum"])


def test_merge_refuses_boundary_skew_and_junk():
    a = histo.Histogram((1.0, 10.0)).snapshot()
    b = histo.Histogram((1.0, 20.0)).snapshot()
    assert histo.merge([a, b]) is None  # deploy skew, not a blend
    assert histo.merge([]) is None
    assert histo.merge([{"histogram": True}]) is None


def test_quantile_interpolates_inside_bucket():
    h = histo.Histogram((10.0, 20.0))
    h.observe_many([5.0] * 10)  # all in the first bucket (0, 10]
    # rank q*10 inside a 10-count bucket spanning 0..10 -> q*10
    assert histo.quantile(h.snapshot(), 0.5) == pytest.approx(5.0)
    assert histo.quantile(h.snapshot(), 0.99) == pytest.approx(9.9)


def test_quantile_clamps_overflow_and_handles_empty():
    h = histo.Histogram((1.0, 2.0))
    assert histo.quantile(h.snapshot(), 0.5) is None  # empty
    h.observe_many([100.0] * 4)  # all +Inf bucket
    assert histo.quantile(h.snapshot(), 0.99) == 2.0  # clamp to last bound
    assert histo.quantile(h.snapshot(), 1.5) is None  # bad q


def test_fleet_quantile_from_merged_buckets_not_quantile_average():
    """The motivating property: rank A all-fast, rank B all-slow — the
    fleet p50 must come from the MERGED distribution (between the two
    modes), which no average of per-rank p50s recovers."""
    fast = histo.Histogram((1.0, 100.0))
    slow = histo.Histogram((1.0, 100.0))
    fast.observe_many([0.5] * 100)
    slow.observe_many([50.0] * 100)
    m = histo.merge([fast.snapshot(), slow.snapshot()])
    q75 = histo.quantile(m, 0.75)
    assert 1.0 < q75 <= 100.0  # lands in the slow mode's bucket
    assert histo.quantile(fast.snapshot(), 0.75) < 1.0


def test_render_parse_round_trip_preserves_every_sample():
    h = histo.Histogram((1.0, 2.5, 10.0))
    h.observe_many([0.5, 2.0, 2.2, 9.0, 99.0])
    snap = h.snapshot(labels={"policy": "canary"})
    lines = histo.render_prometheus("serving_latency_ms", snap)
    text = "\n".join(lines)
    assert 'le="1"' in text and 'le="2.5"' in text and 'le="+Inf"' in text
    assert 'policy="canary"' in text
    parsed = histo.parse_prometheus(text)
    rebuilt = {
        (name, labels.get("le")): value for name, labels, value in parsed
    }
    assert rebuilt[("serving_latency_ms_bucket", "+Inf")] == 5
    assert rebuilt[("serving_latency_ms_count", None)] == 5
    assert rebuilt[("serving_latency_ms_sum", None)] == pytest.approx(
        snap["sum"]
    )


def test_parse_prometheus_skips_malformed_lines():
    text = "\n".join([
        "# HELP x y",
        "good_metric 1.5",
        'labeled{a="b",c="d,e"} 2',
        "torn_line_no_value",
        "bad_value abc",
        "",
    ])
    parsed = histo.parse_prometheus(text)
    assert ("good_metric", {}, 1.5) in parsed
    assert ("labeled", {"a": "b", "c": "d,e"}, 2.0) in parsed
    assert len(parsed) == 2
