"""IMPALA/A3C trainer tests: Pong env mechanics, staleness semantics,
V-trace on-policy degradation, and learning on analytic MDPs (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.algos import impala
from actor_critic_tpu.envs import make_pong, make_two_state_mdp
from actor_critic_tpu.envs.pong import PongState


# ---------------------------------------------------------------- Pong env


def test_pong_reset_shapes_and_dtype():
    env = make_pong(size=42)
    state, obs = env.reset(jax.random.key(0))
    assert obs.shape == (42, 42, 2)
    assert obs.dtype == jnp.uint8
    assert env.spec.obs_shape == (42, 42, 2)
    assert env.spec.discrete and env.spec.action_dim == 3
    # Ball + both paddles rendered.
    assert int(jnp.sum(obs[..., 1] > 0)) > 0


def test_pong_step_runs_vmapped_and_jitted():
    env = make_pong(size=42)
    keys = jax.random.split(jax.random.key(0), 4)
    state, obs = jax.vmap(env.reset)(keys)
    actions = jnp.array([0, 1, 2, 0])
    out = jax.jit(jax.vmap(env.step))(state, actions)
    assert out.obs.shape == (4, 42, 42, 2)
    assert out.obs.dtype == jnp.uint8
    assert out.reward.shape == (4,)
    np.testing.assert_array_equal(np.asarray(out.done), 0.0)


def test_pong_wall_bounce_reflects_vy():
    env = make_pong(size=42)
    state, _ = env.reset(jax.random.key(0))
    # Place the ball about to cross the top wall, moving up.
    state = state._replace(
        ball_x=jnp.float32(21.0), ball_y=jnp.float32(0.5),
        vel_x=jnp.float32(0.0), vel_y=jnp.float32(-1.5),
    )
    out = env.step(state, jnp.int32(0))
    assert float(out.state.vel_y) > 0  # reflected downward
    assert float(out.state.ball_y) >= 0


def test_pong_scoring_and_termination():
    env = make_pong(size=42, points_to_win=1)
    state, _ = env.reset(jax.random.key(0))
    # Ball sailing past the LEFT edge far from the opponent paddle ⇒ the
    # agent scores; with points_to_win=1 the episode terminates (and
    # auto-resets).
    state = state._replace(
        ball_x=jnp.float32(1.5), ball_y=jnp.float32(40.0),
        vel_x=jnp.float32(-2.0), vel_y=jnp.float32(0.0),
        opp_y=jnp.float32(6.0),  # far from the ball
    )
    out = env.step(state, jnp.int32(0))
    assert float(out.reward) == 1.0
    assert float(out.done) == 1.0
    assert float(out.info["terminated"]) == 1.0
    # Auto-reset: fresh episode state (scores back to zero).
    assert int(out.state.player_score) == 0


def test_pong_agent_miss_negative_reward():
    env = make_pong(size=42, points_to_win=5)
    state, _ = env.reset(jax.random.key(0))
    state = state._replace(
        ball_x=jnp.float32(40.5), ball_y=jnp.float32(40.0),
        vel_x=jnp.float32(2.0), vel_y=jnp.float32(0.0),
        player_y=jnp.float32(6.0),
    )
    out = env.step(state, jnp.int32(0))
    assert float(out.reward) == -1.0
    assert float(out.done) == 0.0  # game to 5 continues
    assert int(out.state.opp_score) == 1


def test_pong_paddle_hit_reflects_vx():
    env = make_pong(size=42)
    state, _ = env.reset(jax.random.key(0))
    state = state._replace(
        ball_x=jnp.float32(38.0), ball_y=jnp.float32(21.0),
        vel_x=jnp.float32(2.0), vel_y=jnp.float32(0.0),
        player_y=jnp.float32(21.0),
    )
    out = env.step(state, jnp.int32(0))
    assert float(out.state.vel_x) < 0  # bounced back toward the opponent
    assert float(out.reward) == 0.0


# ---------------------------------------------------------- IMPALA trainer


def test_impala_on_policy_rhos_are_one():
    """With actor_refresh_every=1 the behaviour policy equals the learner
    policy at rollout time, so every clipped ρ is exactly 1."""
    env = make_two_state_mdp()
    cfg = impala.ImpalaConfig(
        num_envs=4, rollout_steps=8, hidden=(16,), actor_refresh_every=1
    )
    state = impala.init_state(env, cfg, jax.random.key(0))
    step = jax.jit(impala.make_train_step(env, cfg))
    state, metrics = step(state)
    np.testing.assert_allclose(float(metrics["mean_rho"]), 1.0, rtol=1e-6)
    state, metrics = step(state)  # still in sync after the refresh
    np.testing.assert_allclose(float(metrics["mean_rho"]), 1.0, rtol=1e-6)


def test_impala_staleness_refresh_schedule():
    """actor_refresh_every=3: actor params lag the learner until step 3."""
    env = make_two_state_mdp()
    cfg = impala.ImpalaConfig(
        num_envs=4, rollout_steps=4, hidden=(16,), actor_refresh_every=3
    )
    state = impala.init_state(env, cfg, jax.random.key(0))
    step = jax.jit(impala.make_train_step(env, cfg))

    def params_equal(a, b):
        return all(
            bool(jnp.all(x == y))
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
        )

    state, _ = step(state)  # step 1: no refresh
    assert not params_equal(state.params, state.actor_params)
    state, _ = step(state)  # step 2: no refresh
    assert not params_equal(state.params, state.actor_params)
    state, _ = step(state)  # step 3: refresh boundary
    assert params_equal(state.params, state.actor_params)


def test_impala_learns_two_state_mdp():
    """IMPALA with a 2-step policy lag still converges on the analytic MDP
    (V-trace corrects the off-policyness)."""
    env = make_two_state_mdp()
    cfg = impala.ImpalaConfig(
        num_envs=16, rollout_steps=8, hidden=(32,), lr=3e-3,
        actor_refresh_every=2, entropy_coef=0.001,
    )
    state, _ = impala.train(env, cfg, num_iterations=800, seed=0)
    net = impala.make_network(env, cfg)
    obs = jnp.eye(2, dtype=jnp.float32)  # both one-hot states
    dist, values = net.apply(state.params, obs)
    probs = jax.nn.softmax(dist.logits, axis=-1)
    # Action 1 is optimal in both states (reward 1 forever).
    assert float(probs[0, 1]) > 0.8
    assert float(probs[1, 1]) > 0.8
    # Critic heads toward V* = 1/(1-γ) = 100 (exact fixed point takes far
    # longer than the test budget; assert it is well on the way).
    assert 50.0 < float(values[0]) <= 110.0


def test_a3c_mode_learns_two_state_mdp():
    env = make_two_state_mdp()
    cfg = impala.ImpalaConfig(
        num_envs=16, rollout_steps=8, hidden=(32,), lr=3e-3,
        correction="none", actor_refresh_every=2, entropy_coef=0.001,
        lam=0.95,
    )
    state, _ = impala.train(env, cfg, num_iterations=400, seed=0)
    net = impala.make_network(env, cfg)
    obs = jnp.eye(2, dtype=jnp.float32)
    dist, _ = net.apply(state.params, obs)
    probs = jax.nn.softmax(dist.logits, axis=-1)
    assert float(probs[0, 1]) > 0.8
    assert float(probs[1, 1]) > 0.8


def test_impala_pixel_smoke():
    """CNN path: a few fused steps on the Pong env produce finite losses."""
    env = make_pong(size=42, points_to_win=1, max_steps=64)
    cfg = impala.ImpalaConfig(num_envs=2, rollout_steps=4)
    state = impala.init_state(env, cfg, jax.random.key(0))
    step = jax.jit(impala.make_train_step(env, cfg), donate_argnums=0)
    for _ in range(2):
        state, metrics = step(state)
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["entropy"]))
    assert int(state.update_step) == 2


def test_impala_config_validation():
    with pytest.raises(ValueError):
        impala.ImpalaConfig(correction="bogus")
    with pytest.raises(ValueError):
        impala.ImpalaConfig(actor_refresh_every=0)
