"""Golden tests for GAE / λ-returns / V-trace (SURVEY.md §4).

Each scan is checked against a naive O(T²) (or recursive) NumPy
implementation on small random trajectories, plus hand-checked edge cases.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.ops import (
    discounted_returns,
    gae,
    lambda_returns,
    n_step_returns,
    vtrace,
)


def naive_gae(rewards, values, dones, bootstrap, gamma, lam):
    T = len(rewards)
    vals_tp1 = np.concatenate([values[1:], [bootstrap]])
    advs = np.zeros(T)
    last = 0.0
    for t in reversed(range(T)):
        nonterm = 1.0 - dones[t]
        delta = rewards[t] + gamma * vals_tp1[t] * nonterm - values[t]
        last = delta + gamma * lam * nonterm * last
        advs[t] = last
    return advs, advs + values


def naive_vtrace(t_logp, b_logp, rewards, values, dones, bootstrap, gamma,
                 rho_bar, c_bar, lam=1.0):
    T = len(rewards)
    rhos = np.exp(t_logp - b_logp)
    crho = np.minimum(rho_bar, rhos)
    cs = lam * np.minimum(c_bar, rhos)
    disc = gamma * (1.0 - dones)
    vals_tp1 = np.concatenate([values[1:], [bootstrap]])
    vs = np.zeros(T)
    acc = 0.0
    for t in reversed(range(T)):
        delta = crho[t] * (rewards[t] + disc[t] * vals_tp1[t] - values[t])
        acc = delta + disc[t] * cs[t] * acc
        vs[t] = acc + values[t]
    vs_tp1 = np.concatenate([vs[1:], [bootstrap]])
    pg_adv = crho * (rewards + disc * vs_tp1 - values)
    return vs, pg_adv


@pytest.fixture
def traj():
    rng = np.random.RandomState(0)
    T = 17
    return dict(
        rewards=rng.randn(T).astype(np.float32),
        values=rng.randn(T).astype(np.float32),
        dones=(rng.rand(T) < 0.2).astype(np.float32),
        bootstrap=np.float32(rng.randn()),
    )


def test_gae_matches_naive(traj):
    gamma, lam = 0.99, 0.95
    adv, ret = gae(
        jnp.asarray(traj["rewards"]),
        jnp.asarray(traj["values"]),
        jnp.asarray(traj["dones"]),
        jnp.asarray(traj["bootstrap"]),
        gamma,
        lam,
    )
    nadv, nret = naive_gae(
        traj["rewards"], traj["values"], traj["dones"], traj["bootstrap"], gamma, lam
    )
    np.testing.assert_allclose(adv, nadv, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ret, nret, rtol=1e-5, atol=1e-5)


def test_gae_hand_computed():
    # Two steps, no dones: delta1 = r1 + γ·V2 − V1 at t=1 uses bootstrap.
    gamma, lam = 0.5, 0.5
    rewards = jnp.array([1.0, 2.0])
    values = jnp.array([0.5, 1.0])
    dones = jnp.zeros(2)
    bootstrap = jnp.asarray(2.0)
    adv, _ = gae(rewards, values, dones, bootstrap, gamma, lam)
    # t=1: delta = 2 + .5*2 - 1 = 2.0 ; adv1 = 2.0
    # t=0: delta = 1 + .5*1 - .5 = 1.0 ; adv0 = 1 + .25*2 = 1.5
    np.testing.assert_allclose(adv, [1.5, 2.0], rtol=1e-6)


def test_gae_done_cuts_bootstrap():
    gamma, lam = 0.99, 0.95
    rewards = jnp.array([1.0, 1.0])
    values = jnp.array([10.0, 10.0])
    dones = jnp.array([0.0, 1.0])  # terminal at the last step
    adv, _ = gae(rewards, values, dones, jnp.asarray(1e6), gamma, lam)
    # Huge bootstrap value must not leak through the terminal.
    assert float(jnp.abs(adv[1])) < 100.0


def test_lambda_returns_lam1_is_mc():
    rng = np.random.RandomState(1)
    T = 11
    rewards = rng.randn(T).astype(np.float32)
    dones = np.zeros(T, np.float32)
    values = rng.randn(T).astype(np.float32)
    bootstrap = np.float32(0.3)
    ret = lambda_returns(
        jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(dones),
        jnp.asarray(bootstrap), 0.9, 1.0,
    )
    mc = discounted_returns(
        jnp.asarray(rewards), jnp.asarray(dones), jnp.asarray(bootstrap), 0.9
    )
    np.testing.assert_allclose(ret, mc, rtol=1e-4, atol=1e-5)


def test_vtrace_matches_naive(traj):
    rng = np.random.RandomState(2)
    T = len(traj["rewards"])
    t_logp = rng.randn(T).astype(np.float32) * 0.3
    b_logp = rng.randn(T).astype(np.float32) * 0.3
    out = vtrace(
        jnp.asarray(t_logp), jnp.asarray(b_logp),
        jnp.asarray(traj["rewards"]), jnp.asarray(traj["values"]),
        jnp.asarray(traj["dones"]), jnp.asarray(traj["bootstrap"]),
        gamma=0.99, rho_bar=1.0, c_bar=1.0,
    )
    nvs, npg = naive_vtrace(
        t_logp, b_logp, traj["rewards"], traj["values"], traj["dones"],
        traj["bootstrap"], 0.99, 1.0, 1.0,
    )
    np.testing.assert_allclose(out.vs, nvs, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(out.pg_advantages, npg, rtol=1e-4, atol=1e-5)


def test_vtrace_on_policy_reduces_to_lambda_return(traj):
    """With π == μ and no clipping, vs must equal the λ-return."""
    T = len(traj["rewards"])
    logp = jnp.zeros(T)
    out = vtrace(
        logp, logp,
        jnp.asarray(traj["rewards"]), jnp.asarray(traj["values"]),
        jnp.asarray(traj["dones"]), jnp.asarray(traj["bootstrap"]),
        gamma=0.99, rho_bar=1e9, c_bar=1e9, lam=0.95,
    )
    ret = lambda_returns(
        jnp.asarray(traj["rewards"]), jnp.asarray(traj["values"]),
        jnp.asarray(traj["dones"]), jnp.asarray(traj["bootstrap"]), 0.99, 0.95,
    )
    np.testing.assert_allclose(out.vs, ret, rtol=1e-4, atol=1e-5)


def test_batched_time_major_broadcast():
    """Same code must serve [T] and [T, E] shapes (vmapped envs)."""
    rng = np.random.RandomState(3)
    T, E = 9, 4
    rewards = rng.randn(T, E).astype(np.float32)
    values = rng.randn(T, E).astype(np.float32)
    dones = (rng.rand(T, E) < 0.15).astype(np.float32)
    bootstrap = rng.randn(E).astype(np.float32)
    adv, ret = gae(
        jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(dones),
        jnp.asarray(bootstrap), 0.99, 0.95,
    )
    assert adv.shape == (T, E)
    for e in range(E):
        nadv, nret = naive_gae(
            rewards[:, e], values[:, e], dones[:, e], bootstrap[e], 0.99, 0.95
        )
        np.testing.assert_allclose(adv[:, e], nadv, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(ret[:, e], nret, rtol=1e-4, atol=1e-5)


def test_n_step_returns():
    rewards = jnp.array([1.0, 1.0, 1.0, 1.0])
    values = jnp.array([2.0, 3.0, 4.0, 5.0])
    dones = jnp.zeros(4)
    bootstrap = jnp.asarray(10.0)
    g = n_step_returns(rewards, values, dones, bootstrap, gamma=0.5, n=2)
    # t=0: r0 + γ r1 + γ² V(s2) = 1 + .5 + .25*4 = 2.5
    # t=2: r2 + γ r3 + γ² V(s4=boot) = 1 + .5 + 2.5 = 4.0
    # t=3: r3 + γ V(boot) = 1 + 5 = 6.0
    np.testing.assert_allclose(g[0], 2.5, rtol=1e-5)
    np.testing.assert_allclose(g[2], 4.0, rtol=1e-5)
    np.testing.assert_allclose(g[3], 6.0, rtol=1e-5)


def test_n_step_returns_done_stops():
    rewards = jnp.array([1.0, 1.0, 1.0])
    values = jnp.array([2.0, 3.0, 4.0])
    dones = jnp.array([0.0, 1.0, 0.0])
    bootstrap = jnp.asarray(10.0)
    g = n_step_returns(rewards, values, dones, bootstrap, gamma=0.5, n=3)
    # t=0: r0 + γ r1, then done → no further rewards, no bootstrap
    np.testing.assert_allclose(g[0], 1.5, rtol=1e-5)


def test_gae_jit_and_grad():
    """The scan must be jit-able and differentiable w.r.t. values."""
    T = 8
    rewards = jnp.ones(T)
    dones = jnp.zeros(T)

    @jax.jit
    def loss(values, bootstrap):
        adv, _ = gae(rewards, values, dones, bootstrap, 0.99, 0.95)
        return jnp.sum(adv**2)

    g = jax.grad(loss)(jnp.zeros(T), jnp.asarray(0.0))
    assert g.shape == (T,)
    assert bool(jnp.all(jnp.isfinite(g)))
