"""Fleet metrics aggregation (telemetry/fleet.py, ISSUE 16): endpoint
announce/discover over the shared mailbox dir (atomic publish, torn
reads tolerated), and the FleetAggregator's merged views — counters
and histogram buckets summing EXACTLY across ranks, gauges rolled up
min/max, dead ranks degrading to `unreachable` instead of failing the
view."""

import json

import pytest

from actor_critic_tpu.telemetry import fleet, histo


# ------------------------------------------------------ announce/discover


def test_announce_then_discover_round_trip(tmp_path):
    fleet.announce_endpoint(tmp_path, 0, "http://127.0.0.1:9100")
    fleet.announce_endpoint(tmp_path, 3, "http://127.0.0.1:9103", seed=7)
    assert fleet.discover_endpoints(tmp_path) == {
        0: "http://127.0.0.1:9100", 3: "http://127.0.0.1:9103",
    }
    ann = fleet.read_endpoint(tmp_path, 3)
    assert ann["rank"] == 3 and ann["seed"] == 7 and ann["pid"] > 0
    # re-announce replaces (a restarted rank's new port wins)
    fleet.announce_endpoint(tmp_path, 0, "http://127.0.0.1:9200")
    assert fleet.discover_endpoints(tmp_path)[0] == "http://127.0.0.1:9200"


def test_announce_leaves_no_tmp_droppings(tmp_path):
    fleet.announce_endpoint(tmp_path, 1, "http://x:1")
    names = [p.name for p in tmp_path.iterdir()]
    assert names == ["telemetry_endpoint_host1.json"]


def test_torn_announce_reads_as_none_not_crash(tmp_path):
    path = fleet.endpoint_file(tmp_path, 2)
    with open(path, "w") as f:
        f.write('{"rank": 2, "url"')  # writer died mid-write
    assert fleet.read_endpoint(tmp_path, 2) is None
    assert fleet.discover_endpoints(tmp_path) == {}
    assert fleet.read_endpoint(tmp_path, 99) is None  # absent
    assert fleet.discover_endpoints(tmp_path / "nope") == {}


# -------------------------------------------------- snapshot reconstruction


def test_snapshots_from_parsed_round_trips_render(tmp_path):
    h = histo.Histogram((1.0, 2.5, 10.0))
    h.observe_many([0.5, 2.0, 9.0, 50.0])
    snap = h.snapshot(labels={"policy": "champ"})
    text = "\n".join(histo.render_prometheus("serving_latency_ms", snap))
    out = fleet.snapshots_from_parsed(histo.parse_prometheus(text))
    key = ("serving_latency_ms", (("policy", "champ"),))
    assert key in out
    back = out[key]
    assert back["buckets"] == snap["buckets"]
    assert back["boundaries"] == list(snap["boundaries"])
    assert back["count"] == snap["count"]
    assert back["sum"] == pytest.approx(snap["sum"])


# ------------------------------------------------------------- aggregator


def _two_rank_aggregator(rank_texts):
    """Aggregator over static endpoints whose scrape is stubbed to the
    given {rank: text} (None = unreachable) — no sockets, deterministic."""
    agg = fleet.FleetAggregator(
        endpoints={r: f"http://stub:{r}" for r in rank_texts}
    )
    agg._fetch = lambda url, _t=rank_texts: _t[int(url.rsplit(":", 1)[1])]
    return agg


def _rank_text(scale: int) -> str:
    h = histo.Histogram((1.0, 10.0))
    h.observe_many([0.5] * scale + [5.0] * scale + [50.0] * scale)
    lines = [
        "actor_critic_up 1",
        f"actor_critic_serving_requests_total {10 * scale}",
        f"actor_critic_rss_bytes {1000 * scale}",
    ] + histo.render_prometheus(
        "actor_critic_serving_latency_ms", h.snapshot(
            labels={"policy": "default"}
        )
    )
    return "\n".join(lines) + "\n"


def test_fleetz_buckets_and_counters_sum_exactly():
    agg = _two_rank_aggregator({0: _rank_text(2), 1: _rank_text(3)})
    z = agg.fleetz()
    assert z["fleet_size"] == 2 and z["reachable"] == [0, 1]
    assert z["counters"]["actor_critic_serving_requests_total"] == 50
    (hist,) = z["histograms"].values()
    # rank0 buckets [2,4,6], rank1 [3,6,9] -> fleet [5,10,15], exactly
    assert hist["buckets"] == [5, 10, 15]
    assert hist["count"] == 15
    # quantiles come from the MERGED buckets
    assert 0.0 < hist["p50"] <= 10.0
    assert hist["p99"] == 10.0  # +Inf bucket clamps to last bound
    assert list(z["histograms"]) == [
        "actor_critic_serving_latency_ms{policy=default}"
    ]


def test_fleetz_dead_rank_degrades_to_unreachable():
    agg = _two_rank_aggregator({0: _rank_text(1), 1: None})
    z = agg.fleetz()
    assert z["reachable"] == [0] and z["unreachable"] == [1]
    assert z["ranks"]["1"] == {"url": "http://stub:1", "up": False}
    assert z["ranks"]["0"]["up"] is True
    # the reachable rank's counters still roll up
    assert z["counters"]["actor_critic_serving_requests_total"] == 10
    json.dumps(z)  # the /fleetz body must be JSON-serializable


def test_merged_metrics_labels_ranks_and_sums_fleet_rows():
    agg = _two_rank_aggregator({0: _rank_text(2), 1: _rank_text(3)})
    body = agg.merged_metrics()
    samples = {
        (name, tuple(sorted(labels.items()))): value
        for name, labels, value in histo.parse_prometheus(body)
    }

    def get(name, **labels):
        return samples[(name, tuple(sorted(labels.items())))]

    assert get("actor_critic_fleet_size") == 2
    assert get("actor_critic_fleet_reachable") == 2
    # per-rank rows carry their rank label
    assert get("actor_critic_serving_requests_total", rank="0") == 20
    assert get("actor_critic_serving_requests_total", rank="1") == 30
    # fleet rollup: counters sum exactly ...
    assert get("actor_critic_serving_requests_total", rank="fleet") == 50
    assert get(
        "actor_critic_serving_latency_ms_bucket",
        le="+Inf", policy="default", rank="fleet",
    ) == 15
    # ... gauges do NOT (min/max, never a manufactured average)
    assert get("actor_critic_rss_bytes", rank="fleet", agg="min") == 2000
    assert get("actor_critic_rss_bytes", rank="fleet", agg="max") == 3000


def test_discovery_plus_static_endpoints_merge(tmp_path):
    fleet.announce_endpoint(tmp_path, 0, "http://a:1")
    agg = fleet.FleetAggregator(
        mailbox_dir=str(tmp_path), endpoints={1: "http://b:2"}
    )
    assert agg.endpoints() == {0: "http://a:1", 1: "http://b:2"}


def test_aggregator_against_real_exporters(tmp_path):
    """End-to-end over real sockets: two TelemetrySessions announce
    into one mailbox; /fleetz sees both up and merges their (shared —
    the gauge registry is process-global, so both exporters render the
    same snapshot) histogram buckets by exact addition."""
    from actor_critic_tpu import telemetry
    from actor_critic_tpu.telemetry import sampler

    mailbox = tmp_path / "mailbox"
    mailbox.mkdir()
    h = histo.Histogram((1.0, 10.0))
    h.observe_many([0.5, 5.0, 5.0])
    snap = h.snapshot(labels={"policy": "default"})
    snap["metric"] = "latency_ms"
    key = sampler.register_gauge(
        "serving", lambda: {
            "requests_total": 10,
            "latency_ms_hist_default": snap,
        },
    )
    sessions = []
    try:
        for rank in (0, 1):
            s = telemetry.TelemetrySession(
                tmp_path / f"host{rank}", sample_resources=False,
                serve_port=0, flight=False,
            )
            fleet.announce_endpoint(mailbox, rank, s.exporter.url)
            sessions.append(s)
        agg = fleet.FleetAggregator(mailbox_dir=str(mailbox))
        z = agg.fleetz()
        assert z["reachable"] == [0, 1]
        hists = [
            v for k, v in z["histograms"].items()
            if "latency_ms" in k and "policy=default" in k
        ]
        assert len(hists) == 1
        # each rank exposes buckets [1, 3, 3]; the fleet view is their
        # exact sum, not an average or a pick
        assert hists[0]["buckets"] == [2, 6, 6]
        assert hists[0]["count"] == 6
    finally:
        sampler.unregister_gauge(key)
        for s in sessions:
            s.close()
