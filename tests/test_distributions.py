"""Distribution math tests: log-probs vs scipy-style references, entropy,
tanh change-of-variables vs numerical integration (SURVEY.md §4)."""

import math

import jax
import jax.numpy as jnp
import numpy as np

from actor_critic_tpu.models import Categorical, DiagGaussian, TanhGaussian


def test_categorical_log_prob_and_entropy():
    logits = jnp.asarray(np.random.RandomState(0).randn(5, 7).astype(np.float32))
    dist = Categorical(logits)
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    actions = jnp.asarray([0, 3, 6, 2, 1])
    lp = np.asarray(dist.log_prob(actions))
    for i, a in enumerate([0, 3, 6, 2, 1]):
        np.testing.assert_allclose(lp[i], math.log(probs[i, a]), rtol=1e-4)
    ent = np.asarray(dist.entropy())
    nent = -(probs * np.log(probs)).sum(-1)
    np.testing.assert_allclose(ent, nent, rtol=1e-4)


def test_categorical_sampling_distribution():
    logits = jnp.log(jnp.asarray([0.1, 0.6, 0.3]))
    dist = Categorical(logits)
    keys = jax.random.split(jax.random.key(0), 20000)
    samples = jax.vmap(dist.sample)(keys)
    freqs = np.bincount(np.asarray(samples), minlength=3) / 20000
    np.testing.assert_allclose(freqs, [0.1, 0.6, 0.3], atol=0.02)


def test_diag_gaussian_log_prob():
    mean = jnp.asarray([0.5, -1.0])
    log_std = jnp.asarray([0.1, -0.3])
    dist = DiagGaussian(mean, log_std)
    x = jnp.asarray([0.7, -0.8])
    # manual
    std = np.exp(np.asarray(log_std))
    z = (np.asarray(x) - np.asarray(mean)) / std
    expected = (-0.5 * (z**2 + math.log(2 * math.pi)) - np.asarray(log_std)).sum()
    np.testing.assert_allclose(float(dist.log_prob(x)), expected, rtol=1e-5)


def test_diag_gaussian_entropy_matches_sampled():
    dist = DiagGaussian(jnp.asarray([0.0, 2.0]), jnp.asarray([0.2, -0.5]))
    keys = jax.random.split(jax.random.key(1), 50000)
    samples = jax.vmap(dist.sample)(keys)
    est = -np.mean(np.asarray(jax.vmap(dist.log_prob)(samples)))
    np.testing.assert_allclose(float(dist.entropy()), est, rtol=0.02)


def test_diag_gaussian_kl_self_is_zero():
    dist = DiagGaussian(jnp.asarray([1.0, -1.0]), jnp.asarray([0.3, 0.0]))
    np.testing.assert_allclose(float(dist.kl(dist)), 0.0, atol=1e-6)


def test_tanh_gaussian_log_prob_change_of_variables():
    """Compare sample_and_log_prob against atanh-based log_prob, and against
    the unstable direct formula log N(pre) − Σ log(1−tanh²(pre))."""
    dist = TanhGaussian.create(jnp.asarray([0.3, -0.2]), jnp.asarray([-0.5, 0.1]))
    key = jax.random.key(2)
    action, logp = dist.sample_and_log_prob(key)
    assert bool(jnp.all(jnp.abs(action) < 1.0))
    # Recompute via atanh path.
    logp2 = dist.log_prob(action)
    np.testing.assert_allclose(float(logp), float(logp2), rtol=1e-4)
    # Direct (unstable) formula on moderate values:
    pre = jnp.arctanh(action)
    direct = dist.base.log_prob(pre) - jnp.sum(jnp.log(1 - jnp.tanh(pre) ** 2))
    np.testing.assert_allclose(float(logp), float(direct), rtol=1e-4)


def test_tanh_gaussian_extreme_stability():
    """Large |pre-tanh| values must not produce inf/nan (SURVEY §7.2.5)."""
    dist = TanhGaussian.create(jnp.asarray([15.0]), jnp.asarray([-3.0]))
    action, logp = dist.sample_and_log_prob(jax.random.key(3))
    assert bool(jnp.isfinite(logp))
    # action numerically == 1.0; atanh path must still be finite
    assert bool(jnp.isfinite(dist.log_prob(action)))


def test_tanh_gaussian_integrates_to_one():
    """∫ p(a) da ≈ 1 over (-1,1) by trapezoid on a 1-d squashed Gaussian."""
    dist = TanhGaussian.create(jnp.asarray([0.2]), jnp.asarray([0.0]))
    grid = jnp.linspace(-1 + 1e-4, 1 - 1e-4, 4001)[:, None]
    dens = jnp.exp(jax.vmap(dist.log_prob)(grid))
    integral = float(jnp.trapezoid(dens, dx=float(grid[1, 0] - grid[0, 0])))
    np.testing.assert_allclose(integral, 1.0, atol=2e-3)


def test_distributions_are_pytrees():
    """Must flow through jit/vmap/scan carries untouched."""
    dist = DiagGaussian(jnp.zeros(3), jnp.zeros(3))
    leaves = jax.tree.leaves(dist)
    assert len(leaves) == 2

    @jax.jit
    def f(d: DiagGaussian):
        return d.entropy()

    assert f(dist).shape == ()
