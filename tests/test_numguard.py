"""Finiteness guards + NaN-safe JSON (ISSUE 14 satellites).

Covers the `utils/numguard.py` primitives, the production gates wired
onto them (publisher / mailbox / policy store / checkpoint), and the
telemetry regression the ISSUE names: a NaN gauge through a LIVE
sampler tick must emit a parseable row with the value nulled instead of
silently dropping the row forever.
"""

import io
import json

import numpy as np
import pytest

from actor_critic_tpu.utils import numguard
from actor_critic_tpu.utils.numguard import (
    NonFiniteError,
    check_finite,
    nonfinite_leaves,
    safe_json_row,
)

# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def test_nonfinite_leaves_names_paths_and_kinds():
    tree = {
        "w": np.array([1.0, np.nan], np.float32),
        "nested": {"b": np.array([np.inf], np.float32)},
        "ints": np.array([1, 2], np.int32),
    }
    bad = dict(nonfinite_leaves(tree, "params"))
    assert bad["params['w'][1]"] == "nan"
    assert bad["params['nested']['b'][0]"] == "inf"
    assert not any("ints" in k for k in bad)


def test_check_finite_passes_finite_denormal_and_int_trees():
    check_finite({"w": np.ones((3,), np.float32)}, "t")
    check_finite({"d": np.array([1e-42], np.float32)}, "t")  # denormal
    check_finite({"i": np.arange(4)}, "t")
    check_finite({"s": "str", "n": None, "f": 1.5}, "t")


def test_check_finite_refuses_with_location():
    with pytest.raises(NonFiniteError) as e:
        check_finite(
            {"w": np.array([0.0, -np.inf], np.float32)}, "unit test"
        )
    assert "unit test" in str(e.value)
    assert "-inf" in str(e.value)


def test_check_finite_handles_namedtuples_and_scalars():
    from collections import namedtuple

    Stats = namedtuple("Stats", "mean scale")
    check_finite(Stats(np.zeros(2), np.full(2, 1e-6)), "t")
    with pytest.raises(NonFiniteError):
        check_finite(Stats(np.zeros(2), float("nan")), "t")


# ---------------------------------------------------------------------------
# safe_json_row
# ---------------------------------------------------------------------------


def test_safe_json_row_nulls_nonfinite_and_reports_once(capsys):
    key = "test_unique_gauge_key_1"
    line = safe_json_row({key: float("nan"), "ok": 2.0})
    row = json.loads(line)
    assert row[key] is None and row["ok"] == 2.0
    first = capsys.readouterr().err
    assert key in first
    # second serialization of the same key: no second report
    safe_json_row({key: float("inf")})
    assert key not in capsys.readouterr().err


def test_safe_json_row_handles_numpy_and_nested():
    line = safe_json_row({
        "np_nan": np.float32("nan"),
        "np_int": np.int64(3),
        "nested": {"v": [1.0, float("inf")]},
    })
    row = json.loads(line)
    assert row["np_nan"] is None
    assert row["np_int"] == 3
    assert row["nested"]["v"][1] is None


def test_safe_json_row_default_str_for_foreign_types():
    class Weird:
        def __str__(self):
            return "weird"

    row = json.loads(safe_json_row({"w": Weird()}, default=str))
    assert "weird" in row["w"]


# ---------------------------------------------------------------------------
# the telemetry regression: a NaN gauge through a LIVE sampler tick
# ---------------------------------------------------------------------------


def test_nan_gauge_through_live_sampler_tick():
    """The ISSUE 14 telemetry crash class: before the fix,
    `json.dumps(..., allow_nan=False)` raised on the NaN gauge and the
    sampler silently dropped EVERY row for the rest of the run. The row
    must now serialize with the gauge nulled."""
    from actor_critic_tpu.telemetry import sampler as sampler_mod
    from actor_critic_tpu.telemetry.sampler import (
        ResourceSampler,
        register_gauge,
        unregister_gauge,
    )

    key = register_gauge("nan_gauge_regression", lambda: float("nan"))
    fh = io.StringIO()
    s = ResourceSampler(fh, interval_s=60.0)
    try:
        s._emit()  # one live tick, thread never started
    finally:
        unregister_gauge(key)
    lines = [ln for ln in fh.getvalue().splitlines() if ln]
    assert lines, "the NaN gauge dropped the row (the pre-fix crash)"
    row = json.loads(lines[-1])  # strict JSON: parses ⇒ no bare NaN
    assert row[key] is None
    assert "recompiles" in row
    assert sampler_mod is not None


def test_jsonl_logger_extra_payload_with_nonfinite(tmp_path):
    from actor_critic_tpu.utils.logging import JsonlLogger

    path = tmp_path / "m.jsonl"
    with JsonlLogger(path) as log:
        log.log(1, {"loss": float("nan")}, nested={"v": float("inf")})
    row = json.loads(path.read_text().splitlines()[0])
    assert row["loss"] is None
    assert row["nested"] == {"v": None}


def test_jsonl_logger_ndarray_extra_never_crashes(tmp_path):
    """Container extras carrying ndarrays (a per-type vector riding a
    metrics row) must serialize as scrubbed lists, and foreign leaf
    types must stringify — the writer can never take the trainer down
    (the review-caught regression of the container pass-through)."""
    from actor_critic_tpu.utils.logging import JsonlLogger

    path = tmp_path / "m.jsonl"
    with JsonlLogger(path) as log:
        log.log(
            1, {"loss": 0.5},
            per_type={"cartpole": np.array([1.0, np.nan], np.float32)},
            weird={"s": {1, 2}},
        )
    row = json.loads(path.read_text().splitlines()[0])
    assert row["per_type"]["cartpole"] == [1.0, None]
    assert isinstance(row["weird"]["s"], str)


def test_gateway_swap_of_poisoned_checkpoint_is_422(tmp_path):
    """The ISSUE 14 swap gate surfacing through the HTTP surface: a
    checkpoint carrying nan params must come back as a 422 refusal (a
    client-actionable rejection; the resident policy keeps serving),
    not the catch-all 500."""
    from actor_critic_tpu.analysis.numsan import _guards_disabled
    from actor_critic_tpu.serving.gateway import ServeGateway
    from actor_critic_tpu.serving.policy_store import (
        PolicyStore,
        export_policy_params,
    )

    class Eng:
        max_rows = 8

        def prepare_params(self, params):
            return {k: np.array(v) for k, v in params.items()}

        def act(self, params, obs):
            return np.asarray(obs)[:, 0]

    good = {"w": np.ones((2,), np.float32)}
    store = PolicyStore()
    store.register("default", Eng(), good)
    ckpt_dir = str(tmp_path / "poisoned")
    with _guards_disabled():  # the only way a poisoned ckpt can exist
        export_policy_params(
            ckpt_dir, {"w": np.array([np.nan, 1.0], np.float32)}
        )
    gw = ServeGateway(store, port=0)
    try:
        status, body = gw.handle_swap(
            {"policy": "default", "checkpoint": ckpt_dir}
        )
    finally:
        gw.close()
    assert status == 422
    assert "refused" in body["error"]
    assert store.get("default").version == 0  # still the good handle


def test_session_event_keeps_nonfinite_forensics(tmp_path):
    """A divergence event CARRYING a non-finite payload field must not
    vanish — that row is exactly the forensic record of the failure."""
    from actor_critic_tpu.telemetry.session import TelemetrySession

    sess = TelemetrySession(
        directory=str(tmp_path / "tele"), sample_resources=False,
    )
    try:
        sess.event("divergence", metric="loss", value=float("nan"))
    finally:
        sess.close()
    rows = [
        json.loads(ln)
        for ln in (tmp_path / "tele" / "events.jsonl")
        .read_text().splitlines()
        if ln
    ]
    div = [r for r in rows if r.get("kind") == "divergence"]
    assert div and div[0]["value"] is None


# ---------------------------------------------------------------------------
# production gates (numsan drives the same ones under seeded schedules)
# ---------------------------------------------------------------------------


def test_policy_publisher_rejects_nonfinite_keeps_good_snapshot():
    from actor_critic_tpu.algos.traj_queue import PolicyPublisher

    good = {"w": np.ones((2,), np.float32)}
    pub = PolicyPublisher(good, version=3)
    with pytest.raises(NonFiniteError):
        pub.publish({"w": np.array([1.0, np.nan], np.float32)}, 4)
    version, params = pub.get()
    assert version == 3
    assert np.all(np.isfinite(params["w"]))


def test_write_params_rejects_nonfinite_keeps_mailbox_file(tmp_path):
    from actor_critic_tpu.parallel.multihost import (
        read_params,
        write_params,
    )

    good = {"w": np.ones((2, 2), np.float32)}
    write_params(str(tmp_path), 0, 1, good)
    with pytest.raises(NonFiniteError):
        write_params(
            str(tmp_path), 0, 2,
            {"w": np.full((2, 2), np.inf, np.float32)},
        )
    out = read_params(str(tmp_path), 0, good)
    assert out is not None and out[0] == 1
    assert np.all(np.isfinite(out[1]["w"]))


def test_checkpointer_refuses_nonfinite_commit(tmp_path):
    from actor_critic_tpu.utils.checkpoint import Checkpointer

    state = {"w": np.ones((2,), np.float32)}
    with Checkpointer(str(tmp_path / "ck")) as ckpt:
        ckpt.save(0, state, force=True)
        ckpt.wait()
        with pytest.raises(NonFiniteError):
            ckpt.save(
                1, {"w": np.array([np.nan, 1.0], np.float32)},
                force=True,
            )
        assert ckpt.latest_step() == 0
        restored = ckpt.restore(state, 0)
        assert np.all(np.isfinite(np.asarray(restored["w"])))


def test_guards_are_one_seam(monkeypatch):
    """Every production gate routes through numguard.check_finite — the
    seam numsan's reverted-guard mode relies on. Verify the no-op
    monkeypatch really opens the publisher gate."""
    from actor_critic_tpu.algos.traj_queue import PolicyPublisher

    monkeypatch.setattr(numguard, "check_finite", lambda *a, **k: None)
    pub = PolicyPublisher({"w": np.ones(1, np.float32)}, version=0)
    pub.publish({"w": np.array([np.nan], np.float32)}, 1)  # no raise
    assert numguard.nonfinite_leaves(pub.get()[1])
