"""bench.py `_last_green` driver-contract tests (VERDICT r4 weak #1).

The tunnel-dead error payload embeds the newest green capture; this is
the artifact the driver reads on a red round, so its robustness matters:
one malformed evidence file must never break the one-JSON-line contract.
"""

import importlib.util
import json
import os

_spec = importlib.util.spec_from_file_location(
    "bench_root", os.path.join(os.path.dirname(__file__), "..", "bench.py")
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _write(path, text):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(text)


def test_last_green_picks_newest_valid(tmp_path):
    _write(
        tmp_path / "results" / "bench_tpu_green_r04.json",
        json.dumps({"value": 1.0e9, "unit": "u", "vs_baseline": 1000.0}),
    )
    newer = tmp_path / "runs" / "bench_tpu_green.json"
    _write(newer, json.dumps({"value": 2.0e9, "unit": "u", "vs_baseline": 2000.0}))
    os.utime(
        tmp_path / "results" / "bench_tpu_green_r04.json", (1_000_000, 1_000_000)
    )
    green = bench._last_green(root=str(tmp_path))
    assert green is not None
    assert green["value"] == 2.0e9
    assert green["evidence_path"] == os.path.join("runs", "bench_tpu_green.json")
    assert green["captured_at"].endswith("Z")


def test_last_green_survives_malformed_files(tmp_path):
    # String value (would TypeError on `> 0`), binary garbage, empty file,
    # truncated JSON — none may break the scan; the one valid file wins.
    _write(tmp_path / "runs" / "bench_tpu_green.json", '{"value": "123"}')
    (tmp_path / "results").mkdir()
    (tmp_path / "results" / "bench_tpu_green_r01.json").write_bytes(b"\xff\xfe\x00")
    _write(tmp_path / "results" / "bench_tpu_green_r02.json", "")
    _write(tmp_path / "results" / "bench_tpu_green_r03.json", '{"value": 5')
    _write(
        tmp_path / "results" / "bench_tpu_green_r04.json",
        json.dumps({"value": 3.0e9, "unit": "u"}),
    )
    green = bench._last_green(root=str(tmp_path))
    assert green is not None and green["value"] == 3.0e9


def test_last_green_none_when_no_evidence(tmp_path):
    assert bench._last_green(root=str(tmp_path)) is None


def test_last_green_rejects_bool_value(tmp_path):
    # JSON `true` is a Python bool, which IS an int: isinstance(True,
    # (int, float)) passes and True > 0 holds, so without the explicit
    # bool exclusion a {"value": true} line would pass as green evidence.
    _write(tmp_path / "runs" / "bench_tpu_green.json", '{"value": true}')
    assert bench._last_green(root=str(tmp_path)) is None
    # ...and a bool must not shadow a REAL record either.
    _write(
        tmp_path / "results" / "bench_tpu_green_r01.json",
        json.dumps({"value": 7.0e8, "unit": "u"}),
    )
    green = bench._last_green(root=str(tmp_path))
    assert green is not None and green["value"] == 7.0e8


def test_last_green_prefers_in_record_timestamp_over_mtime(tmp_path):
    """A committed results file's mtime is CHECKOUT time; a timestamp
    recorded inside the JSON line (numeric `ts` or ISO `captured_at`)
    must win the recency comparison and feed the reported captured_at."""
    older_mtime = tmp_path / "results" / "bench_tpu_green_r01.json"
    _write(
        older_mtime,
        json.dumps({"value": 1.0e9, "unit": "u", "ts": 2_000_000_000}),
    )
    os.utime(older_mtime, (1_000, 1_000))  # ancient mtime, newest in-record ts
    newer_mtime = tmp_path / "runs" / "bench_tpu_green.json"
    _write(newer_mtime, json.dumps({"value": 2.0e9, "unit": "u"}))  # mtime = now
    green = bench._last_green(root=str(tmp_path))
    assert green is not None
    assert green["value"] == 1.0e9  # in-record ts (2033) beats checkout mtime
    assert green["captured_at"] == "2033-05-18T03:33:20Z"

    # ISO captured_at works the same way.
    _write(
        newer_mtime,
        json.dumps({"value": 3.0e9, "unit": "u",
                    "captured_at": "2034-01-01T00:00:00Z"}),
    )
    green = bench._last_green(root=str(tmp_path))
    assert green is not None and green["value"] == 3.0e9


def test_error_line_embeds_green_and_stays_parseable(tmp_path):
    # The whole point: the error payload must carry the evidence embed
    # when evidence exists — asserted unconditionally against a fixture
    # tree, so a broken embed cannot silently pass.
    _write(
        tmp_path / "runs" / "bench_tpu_green.json",
        json.dumps({"value": 4.0e9, "unit": "u", "vs_baseline": 4000.0}),
    )
    rec = json.loads(bench._error_line("tunnel dead", root=str(tmp_path)))
    assert rec["error"] == "tunnel dead"
    assert rec["value"] == 0.0
    assert rec["metric"] == bench.METRIC
    assert rec["last_green"]["value"] == 4.0e9

    # And with NO evidence: still one parseable JSON, no embed.
    empty = tmp_path / "empty"
    empty.mkdir()
    rec2 = json.loads(bench._error_line("tunnel dead", root=str(empty)))
    assert rec2["value"] == 0.0 and "last_green" not in rec2
