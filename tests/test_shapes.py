"""Shape/dtype smoke tests (SURVEY.md §4 'Unit (shapes/dtypes)').

`jax.eval_shape` traces every algorithm's fused train step WITHOUT
executing it, and chex asserts the output state is shape/dtype-identical
to the input — the invariant donation and the scan-carry contract both
depend on. Covers all five algorithm families, including the CNN/uint8
pixel path (IMPALA on Pong), in milliseconds.
"""

import chex
import jax
import pytest

from actor_critic_tpu.algos import a2c, ddpg, impala, ppo, sac
from actor_critic_tpu.envs import make_cartpole, make_point_mass, make_pong


CASES = [
    (
        a2c,
        make_cartpole,
        a2c.A2CConfig(num_envs=4, rollout_steps=3, hidden=(8,)),
    ),
    (
        ppo,
        make_cartpole,
        ppo.PPOConfig(
            num_envs=4, rollout_steps=3, epochs=2, num_minibatches=2,
            hidden=(8,), anneal_iters=5, lr_final=0.0,
        ),
    ),
    (
        impala,
        make_pong,
        impala.ImpalaConfig(num_envs=2, rollout_steps=3, hidden=(8,)),
    ),
    (
        ddpg,
        make_point_mass,
        ddpg.td3_config(
            num_envs=4, steps_per_iter=2, updates_per_iter=1,
            buffer_capacity=32, batch_size=4, warmup_steps=0, hidden=(8,),
        ),
    ),
    (
        sac,
        make_point_mass,
        sac.SACConfig(
            num_envs=4, steps_per_iter=2, updates_per_iter=1,
            buffer_capacity=32, batch_size=4, warmup_steps=0, hidden=(8,),
        ),
    ),
]


@pytest.mark.parametrize(
    "mod,make_env,cfg", CASES,
    ids=["a2c", "ppo", "impala_pixels", "td3", "sac"],
)
def test_train_step_preserves_state_shapes(mod, make_env, cfg):
    env = make_env()
    state = mod.init_state(env, cfg, jax.random.key(0))
    step = mod.make_train_step(env, cfg)
    out_state, metrics = jax.eval_shape(step, state)
    # The carry contract: donation/scan require bitwise-identical
    # structure, shapes, and dtypes across iterations.
    chex.assert_trees_all_equal_shapes_and_dtypes(state, out_state)
    for k, v in metrics.items():
        assert v.shape == (), f"metric {k} is not scalar: {v.shape}"


def test_ppo_a2c_pixel_networks_use_cnn():
    """PPO/A2C must route 3-D (pixel) observations through the Nature
    CNN like IMPALA does — with the MLP torso a [B,H,W,C] batch produces
    garbage shapes. Regression for the round-3 fix."""
    import jax.numpy as jnp

    env = make_pong(size=36)
    for make in (lambda: ppo.make_network(env.spec, ppo.PPOConfig()),
                 lambda: a2c.make_network(env, a2c.A2CConfig())):
        net = make()
        obs = jnp.zeros((2, *env.spec.obs_shape), jnp.uint8)
        params = net.init(jax.random.key(0), obs)
        assert any(
            "conv" in "/".join(str(p.key) for p in path)
            for path, _ in jax.tree_util.tree_flatten_with_path(params)[0]
        ), "pixel obs did not route through the CNN torso"
        dist, value = net.apply(params, obs)
        assert value.shape == (2,)
        assert dist.logits.shape == (2, env.spec.action_dim)
