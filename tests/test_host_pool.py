"""HostEnvPool protocol tests (gymnasium-backed; no MuJoCo needed)."""

import numpy as np
import pytest

gym = pytest.importorskip("gymnasium")

from actor_critic_tpu.envs.host_pool import HostEnvPool, RunningMeanStd


def test_running_mean_std_matches_numpy():
    rms = RunningMeanStd((3,))
    rng = np.random.RandomState(0)
    chunks = [rng.randn(17, 3) * 2.0 + 1.0 for _ in range(5)]
    for c in chunks:
        rms.update(c)
    allx = np.concatenate(chunks)
    np.testing.assert_allclose(rms.mean, allx.mean(0), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(rms.var, allx.var(0), rtol=1e-3, atol=1e-4)


def test_pool_protocol_cartpole():
    pool = HostEnvPool("CartPole-v1", num_envs=3, seed=0, normalize_obs=True)
    assert pool.spec.discrete and pool.spec.action_dim == 2
    obs = pool.reset()
    assert obs.shape == (3, 4) and obs.dtype == np.float32
    total_done = 0
    for t in range(250):
        out = pool.step(np.ones(3, np.int64))
        assert out.obs.shape == (3, 4)
        assert out.reward.shape == (3,)
        if out.done.any():
            total_done += int(out.done.sum())
            # final_obs rows where done differ from the fresh-reset obs rows
            i = int(np.nonzero(out.done)[0][0])
            assert not np.allclose(out.final_obs[i], out.obs[i])
        else:
            np.testing.assert_array_equal(out.final_obs, out.obs)
    assert total_done > 0, "constant-action CartPole must terminate episodes"
    # raw rewards are unnormalized (always 1.0 in CartPole)
    np.testing.assert_allclose(out.raw_reward, np.ones(3))
    pool.close()


def test_pool_state_roundtrip():
    pool = HostEnvPool("CartPole-v1", num_envs=2, seed=1)
    pool.reset()
    for _ in range(30):
        pool.step(np.zeros(2, np.int64))
    st = pool.get_state()
    pool2 = HostEnvPool("CartPole-v1", num_envs=2, seed=1)
    pool2.set_state(st)
    np.testing.assert_allclose(pool2.obs_rms.mean, pool.obs_rms.mean)
    np.testing.assert_allclose(pool2.ret_rms.var, pool.ret_rms.var)
    pool.close()
    pool2.close()


def test_pool_clips_continuous_actions():
    pytest.importorskip("mujoco")
    pool = HostEnvPool("HalfCheetah-v5", num_envs=1, seed=0)
    pool.reset()
    out = pool.step(np.full((1, 6), 100.0, np.float32))  # way out of bounds
    assert np.isfinite(out.obs).all()
    pool.close()


def test_scale_actions_affine_maps_to_bounds():
    """scale_actions=True: policy action a∈[-1,1] executes as
    mid + half·a on the env's Box — verified against a directly-stepped
    native Pendulum (bounds ±2) from an identical injected state."""
    import numpy as np

    from actor_critic_tpu.envs.native_pool import NativeVecEnv

    start = np.array([[0.3, 0.0]], np.float64)

    pool = HostEnvPool(
        "Pendulum-v1", num_envs=1, seed=0, backend="native",
        normalize_obs=False, normalize_reward=False, scale_actions=True,
    )
    pool.reset()
    pool._envs.set_state(start)
    out = pool.step(np.array([[0.5]], np.float32))  # → torque 1.0

    ref = NativeVecEnv("Pendulum-v1", num_envs=1)
    ref.reset(seed=0)
    ref.set_state(start)
    robs, rrew, *_ = ref.step(np.array([[1.0]], np.float32))
    np.testing.assert_allclose(out.obs[0], robs[0], rtol=1e-6)
    np.testing.assert_allclose(out.raw_reward[0], rrew[0], rtol=1e-6)

    # Out-of-range policy actions saturate at the bound (torque 2.0).
    pool._envs.set_state(start)
    out_hi = pool.step(np.array([[1.7]], np.float32))
    ref.set_state(start)
    robs2, *_ = ref.step(np.array([[2.0]], np.float32))
    np.testing.assert_allclose(out_hi.obs[0], robs2[0], rtol=1e-6)

    # The eval companion pool inherits the convention.
    assert pool.eval_pool(num_envs=1).scales_actions is True
    pool.close()


def test_scale_actions_rejects_unbounded_or_discrete():
    import numpy as np
    import pytest as _pytest

    from actor_critic_tpu.envs.host_pool import scalable_bounds

    with _pytest.raises(ValueError, match="finite continuous"):
        HostEnvPool("CartPole-v1", num_envs=1, scale_actions=True)
    # Infinite Box bounds (no installed env has them, so the predicate
    # is unit-tested directly): scaled actions would all be inf/nan.
    assert not scalable_bounds(
        False, np.array([-np.inf]), np.array([np.inf])
    )
    assert not scalable_bounds(False, np.array([-1.0]), np.array([np.inf]))
    assert scalable_bounds(False, np.array([-1.0]), np.array([1.0]))
    assert not scalable_bounds(True, None, None)
