"""Unit tests for the shape/padding lint dimension (ISSUE 20 static
half): analysis/shape_model.py's per-scope padding flow and the three
analysis/shapes.py passes' discharge rules, beyond what the fixture
pairs in tests/test_jaxlint.py pin down.

AST-only (nothing here imports the scanned source), CPU-safe, fast.
"""

import ast
import textwrap

from actor_critic_tpu import analysis
from actor_critic_tpu.analysis import shape_model

CHECKS = ("pad-mask-discipline", "mask-propagation", "slice-before-commit")


def _mod(src: str) -> analysis.ModuleInfo:
    return analysis.ModuleInfo("x.py", "x.py", textwrap.dedent(src))


def _run(src: str, checks=CHECKS):
    return analysis.run_checks([_mod(src)], checks=checks)


def _flow(src: str, name: str) -> shape_model.ScopeFlow:
    mod = _mod(src)
    for flow in shape_model.module_flows(mod):
        if shape_model.scope_name(flow.scope) == name:
            return flow
    raise AssertionError(f"no scope named {name}")


# ---------------------------------------------------------------------------
# shape model facts
# ---------------------------------------------------------------------------


def test_model_binds_producer_and_mask():
    flow = _flow(
        """
        from actor_critic_tpu.utils.compile_cache import pad_to_bucket

        def f(obs, buckets):
            padded, mask = pad_to_bucket(obs, buckets)
            return padded
        """,
        "f",
    )
    ret = [s for s in flow.stmts if isinstance(s, ast.Return)][0]
    env = flow.env_before[id(ret)]
    assert set(env) == {"padded"}
    assert env["padded"].producer == "pad_to_bucket"
    assert env["padded"].mask == "mask"
    assert "mask" in flow.masks


def test_model_discarded_mask_is_none():
    flow = _flow(
        """
        from actor_critic_tpu.utils.compile_cache import pad_to_bucket

        def f(obs, buckets):
            padded, _ = pad_to_bucket(obs, buckets)
            return padded
        """,
        "f",
    )
    ret = [s for s in flow.stmts if isinstance(s, ast.Return)][0]
    assert flow.env_before[id(ret)]["padded"].mask is None


def test_model_propagates_through_wrappers_and_clears_on_slice():
    flow = _flow(
        """
        import jax
        from actor_critic_tpu.utils.compile_cache import pad_to_bucket

        def f(obs, buckets, n):
            padded, _ = pad_to_bucket(obs, buckets)
            staged = jax.device_put(padded)
            valid = padded[:n]
            return staged, valid
        """,
        "f",
    )
    ret = [s for s in flow.stmts if isinstance(s, ast.Return)][0]
    env = flow.env_before[id(ret)]
    assert "staged" in env  # preserving wrapper propagates the binding
    assert "valid" not in env  # slice-back clears it
    assert "padded" in flow.sliced


def test_model_mixture_inline_mask_multiply_is_disciplined():
    # the mixture obs contract: jnp.pad(...) * mask in ONE expression
    flow = _flow(
        """
        import jax.numpy as jnp

        def f(obs, widths, masks, i):
            wide = jnp.pad(obs, (0, widths[i])) * masks[i]
            return wide
        """,
        "f",
    )
    ret = [s for s in flow.stmts if isinstance(s, ast.Return)][0]
    assert flow.env_before[id(ret)] == {}


def test_model_rebind_clears_padded_fact():
    flow = _flow(
        """
        import jax.numpy as jnp

        def f(x, extra):
            wide = jnp.pad(x, (0, extra))
            wide = jnp.zeros_like(x)
            return wide
        """,
        "f",
    )
    ret = [s for s in flow.stmts if isinstance(s, ast.Return)][0]
    assert flow.env_before[id(ret)] == {}


# ---------------------------------------------------------------------------
# pass discharge rules
# ---------------------------------------------------------------------------


def test_wrapped_arg_still_flags_mask_propagation():
    findings = _run(
        """
        import jax
        from actor_critic_tpu.utils.compile_cache import pad_to_bucket

        def f(program, params, obs, buckets):
            padded, _ = pad_to_bucket(obs, buckets)
            out = program(params, jax.device_put(padded))
            return out
        """
    )
    assert [f.check for f in findings] == ["mask-propagation"]


def test_downstream_slice_discharges_mask_propagation():
    findings = _run(
        """
        import numpy as np
        from actor_critic_tpu.utils.compile_cache import pad_to_bucket

        def f(program, params, obs, buckets, n):
            padded, _ = pad_to_bucket(obs, buckets)
            out = program(params, padded)
            return np.asarray(out)[:n]
        """
    )
    assert findings == []


def test_where_keyword_discharges_pad_mask_discipline():
    findings = _run(
        """
        import jax.numpy as jnp
        from actor_critic_tpu.utils.compile_cache import pad_to_bucket

        def f(obs, buckets):
            padded, mask = pad_to_bucket(obs, buckets)
            return jnp.mean(padded, where=mask > 0.5)
        """
    )
    assert findings == []


def test_method_form_reduction_is_flagged():
    findings = _run(
        """
        from actor_critic_tpu.utils.compile_cache import pad_to_bucket

        def f(obs, buckets):
            padded, mask = pad_to_bucket(obs, buckets)
            return padded.mean()
        """
    )
    assert [f.check for f in findings] == ["pad-mask-discipline"]


def test_commit_callee_belongs_to_slice_before_commit_only():
    findings = _run(
        """
        from actor_critic_tpu.utils.compile_cache import pad_to_bucket

        def f(ring, obs, buckets):
            padded, _ = pad_to_bucket(obs, buckets)
            ring.put(padded, version=1)
        """
    )
    assert [f.check for f in findings] == ["slice-before-commit"]


def test_producer_def_bodies_are_exempt():
    # pad helpers construct the pad on purpose; their own internals
    # must not self-flag
    findings = _run(
        """
        import jax.numpy as jnp

        def _pad_lanes(Ep, *arrays):
            out = []
            for a in arrays:
                wide = jnp.pad(a, ((0, 0), (0, Ep - a.shape[-1])))
                out.append(wide)
            return out
        """
    )
    assert findings == []


def test_inline_suppression_covers_the_deliberate_site():
    findings = _run(
        """
        from actor_critic_tpu.utils.compile_cache import pad_to_bucket

        def f(program, params, obs, buckets):
            padded, _ = pad_to_bucket(obs, buckets)
            # jaxlint: disable=mask-propagation (timing-only dispatch)
            out = program(params, padded)
            return out
        """
    )
    assert findings == []


def test_library_elementwise_calls_do_not_flag():
    findings = _run(
        """
        import jax.numpy as jnp
        from actor_critic_tpu.utils.compile_cache import pad_to_bucket

        def f(obs, buckets, n):
            padded, _ = pad_to_bucket(obs, buckets)
            scaled = jnp.tanh(padded)
            return scaled[:n]
        """
    )
    assert findings == []
