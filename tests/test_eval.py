"""Greedy-eval programs: every algo's make_eval_fn runs jitted and a
trained A2C policy evaluates far above a random one (SURVEY.md §3.4)."""

import jax
import pytest

from actor_critic_tpu.algos import a2c, ddpg, impala, ppo, sac
from actor_critic_tpu.envs import make_cartpole, make_point_mass, make_two_state_mdp


@pytest.mark.parametrize(
    "mod,cfg,make_env",
    [
        (a2c, a2c.A2CConfig(num_envs=8, rollout_steps=4, hidden=(16,)), make_cartpole),
        (ppo, ppo.PPOConfig(num_envs=8, rollout_steps=8, num_minibatches=2,
                            hidden=(16,)), make_cartpole),
        (impala, impala.ImpalaConfig(num_envs=8, rollout_steps=4,
                                     hidden=(16,)), make_cartpole),
        (ddpg, ddpg.DDPGConfig(num_envs=8, steps_per_iter=2, batch_size=32,
                               buffer_capacity=512, hidden=(16,)), make_point_mass),
        (sac, sac.SACConfig(num_envs=8, steps_per_iter=2, batch_size=32,
                            buffer_capacity=512, hidden=(16,)), make_point_mass),
    ],
)
def test_eval_fn_runs(mod, cfg, make_env):
    env = make_env()
    state = mod.init_state(env, cfg, jax.random.key(0))
    eval_fn = jax.jit(mod.make_eval_fn(env, cfg), static_argnums=(2, 3))
    ret = eval_fn(state, jax.random.key(1), 4, 16)
    assert ret.shape == ()
    float(ret)  # materializes; must be finite-ish


def test_trained_policy_evals_higher():
    env = make_two_state_mdp()
    cfg = a2c.A2CConfig(num_envs=32, rollout_steps=8, lr=3e-3, gamma=0.9,
                        hidden=(32,))
    state = a2c.init_state(env, cfg, jax.random.key(0))
    eval_fn = jax.jit(a2c.make_eval_fn(env, cfg), static_argnums=(2, 3))
    before = float(eval_fn(state, jax.random.key(1), 16, 32))
    step = jax.jit(a2c.make_train_step(env, cfg), donate_argnums=0)
    for _ in range(300):
        state, _ = step(state)
    after = float(eval_fn(state, jax.random.key(1), 16, 32))
    assert after > before + 1.0, (before, after)
