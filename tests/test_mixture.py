"""Scenario-mixture fleet tests (ISSUE 11): spec parsing, deterministic
heterogeneous fleets, type preservation across auto_reset, bitwise
padded-interface equivalence with homogeneous fleets, curriculum
re-weighting + checkpoint/resume, per-type eval, and a fused A2C smoke
run stepping all four member types in one XLA program."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.envs import make_cartpole, make_mixture
from actor_critic_tpu.envs import mixture as mx

# One shared 4-type fleet env (and one fleet width) for the read-only
# fleet tests below: the per-instance `lax.switch` over four member
# step/reset bodies is an expensive CPU compile, and JAX's eager op
# cache only reuses the compiled switch across calls on the SAME env
# closure at the SAME shapes.
MIX4 = make_mixture("cartpole,pendulum,acrobot,maze", randomize=0.2)
FLEET_N = 64


class TestSpecParsing:
    def test_weights_and_defaults(self):
        parsed = mx.parse_mixture_spec("cartpole*2,pendulum, acrobot")
        assert parsed == [
            ("cartpole", 2.0), ("pendulum", 1.0), ("acrobot", 1.0)
        ]

    def test_rejects_unknown_duplicate_and_bad_weights(self):
        with pytest.raises(ValueError, match="unknown mixture member"):
            mx.parse_mixture_spec("cartpole,frogger")
        with pytest.raises(ValueError, match="duplicate"):
            mx.parse_mixture_spec("cartpole,cartpole")
        with pytest.raises(ValueError, match="bad weight"):
            mx.parse_mixture_spec("cartpole*fast")
        with pytest.raises(ValueError, match=">= 0"):
            mx.parse_mixture_spec("cartpole*-1")
        with pytest.raises(ValueError, match="all be zero"):
            mx.parse_mixture_spec("cartpole*0,maze*0")
        with pytest.raises(ValueError, match="no members"):
            mx.parse_mixture_spec("")

    def test_padded_interface_spec(self):
        env = make_mixture("cartpole,pendulum,acrobot,maze")
        # obs padded to the widest member (maze: 13); one discrete action
        # space wide enough for every member (action_bins=5 > maze's 4).
        assert env.spec.obs_shape == (13,)
        assert env.spec.discrete and env.spec.action_dim == 5
        assert env.member_names == ("cartpole", "pendulum", "acrobot", "maze")
        masks = np.asarray(env.obs_masks)
        assert masks.shape == (4, 13)
        np.testing.assert_array_equal(masks.sum(axis=1), [4, 3, 6, 13])

    def test_member_kwargs_reach_makers(self):
        env = make_mixture(
            "cartpole,maze", member_kwargs={"maze": {"size": 5}}
        )
        # 5x5 maze still emits the fixed 13-wide egocentric obs.
        assert env.member_specs[1].obs_shape == (13,)
        with pytest.raises(ValueError, match="non-member"):
            make_mixture("cartpole", member_kwargs={"pendulum": {}})


class TestFleet:
    def test_heterogeneous_fleet_deterministic(self):
        """Same keys => same types AND same obs, bitwise — the fleet
        reproducibility contract extended to type draws."""
        env = MIX4
        keys = jax.random.split(jax.random.key(0), FLEET_N)
        s1, o1 = jax.vmap(env.reset)(keys)
        s2, o2 = jax.vmap(env.reset)(keys)
        types = np.asarray(s1.type_id)
        assert set(np.unique(types)) == {0, 1, 2, 3}
        np.testing.assert_array_equal(types, np.asarray(s2.type_id))
        np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))

    def test_weighted_type_draw(self):
        env = make_mixture("cartpole*9,maze")
        keys = jax.random.split(jax.random.key(1), 256)
        s, _ = jax.vmap(env.reset)(keys)
        frac_cart = float((np.asarray(s.type_id) == 0).mean())
        assert frac_cart > 0.8  # 9:1 draw weights

    def test_obs_lanes_masked(self):
        """Padded lanes beyond a member's width are exactly zero."""
        env = MIX4
        keys = jax.random.split(jax.random.key(2), FLEET_N)
        s, o = jax.vmap(env.reset)(keys)
        out = jax.vmap(env.step)(
            s, jnp.zeros(FLEET_N, jnp.int32)
        )
        masks = np.asarray(env.obs_masks)[np.asarray(s.type_id)]
        for arr in (np.asarray(o), np.asarray(out.obs),
                    np.asarray(out.info["final_obs"])):
            np.testing.assert_array_equal(arr * (1.0 - masks), 0.0)

    def test_type_preserved_across_auto_reset(self):
        """Default mixture: an episode end re-rolls the member's
        scenario but never its TYPE."""
        env = MIX4
        keys = jax.random.split(jax.random.key(3), FLEET_N)
        s, _ = jax.vmap(env.reset)(keys)
        # Force every member's episode to truncate on the next step.
        s = s._replace(members=tuple(
            m._replace(t=jnp.full_like(m.t, 10_000)) for m in s.members
        ))
        out = jax.vmap(env.step)(s, jnp.zeros(FLEET_N, jnp.int32))
        assert (np.asarray(out.done) == 1.0).all()
        np.testing.assert_array_equal(
            np.asarray(out.state.type_id), np.asarray(s.type_id)
        )
        # ... while the active cartpole instances re-rolled their
        # scenario (fresh per-episode randomization through the member's
        # own auto_reset).
        cart_idx = np.asarray(s.type_id) == 0
        before = np.asarray(s.members[0].scenario.masspole)[cart_idx]
        after = np.asarray(out.state.members[0].scenario.masspole)[cart_idx]
        assert (before != after).all()

    def test_single_type_mixture_bitwise_equals_homogeneous(self):
        """The padded interface is a view, not a different simulation:
        a one-type mixture's masked obs/reward/done equal the plain
        member fleet bit-for-bit across steps AND auto-resets — even in
        redraw mode (a draw landing on the same type keeps the member's
        own auto-reset result)."""
        menv = make_mixture("cartpole", redraw_types=True)
        cenv = make_cartpole()
        keys = jax.random.split(jax.random.key(4), 8)
        ms, _ = jax.vmap(menv.reset)(keys)
        cs = ms.members[0]  # the embedded member fleet, bit-identical start
        mstep = jax.jit(jax.vmap(menv.step))
        cstep = jax.jit(jax.vmap(cenv.step))
        acts = jax.random.randint(jax.random.key(5), (60, 8), 0, 2)
        saw_done = False
        for t in range(60):
            mout = mstep(ms, acts[t])
            cout = cstep(cs, acts[t])
            np.testing.assert_array_equal(
                np.asarray(mout.obs)[:, :4], np.asarray(cout.obs)
            )
            np.testing.assert_array_equal(
                np.asarray(mout.reward), np.asarray(cout.reward)
            )
            np.testing.assert_array_equal(
                np.asarray(mout.done), np.asarray(cout.done)
            )
            saw_done |= bool(np.asarray(mout.done).any())
            ms, cs = mout.state, cout.state
        assert saw_done  # the equivalence covered at least one auto-reset

    def test_action_adapter_continuous_member(self):
        """Discrete mixture actions map onto the continuous member's
        torque levels: extreme indices produce opposite-sign dynamics."""
        env = make_mixture("pendulum", action_bins=5)
        s, _ = env.reset(jax.random.key(6))
        lo = env.step(s, jnp.asarray(0))
        hi = env.step(s, jnp.asarray(4))
        mid = env.step(s, jnp.asarray(2))
        # Same pre-step state: reward is computed pre-torque except the
        # torque cost, so compare the post-step velocity instead.
        v_lo = float(lo.state.members[0].theta_dot)
        v_hi = float(hi.state.members[0].theta_dot)
        v_mid = float(mid.state.members[0].theta_dot)
        assert v_lo < v_mid < v_hi or v_lo > v_mid > v_hi


class TestCurriculum:
    def test_parse_and_validation(self):
        cur = mx.parse_curriculum("100:1,2;400:0,1", ("cartpole", "maze"))
        assert cur.thresholds == (100.0, 400.0)
        assert cur.stage_weights == ((1.0, 2.0), (0.0, 1.0))
        assert cur.n_stages == 3
        with pytest.raises(ValueError, match="weights"):
            mx.parse_curriculum("100:1,2,3", ("cartpole", "maze"))
        with pytest.raises(ValueError, match="increasing"):
            mx.parse_curriculum("100:1,2;50:2,1", ("cartpole", "maze"))
        with pytest.raises(ValueError, match="no stages"):
            mx.parse_curriculum(";", ("cartpole", "maze"))
        with pytest.raises(ValueError, match="not 'THRESHOLD"):
            mx.parse_curriculum("100", ("cartpole", "maze"))

    def test_controller_advances_and_syncs(self):
        cur = mx.parse_curriculum("10:1,2;20:0,1", ("cartpole", "maze"))
        ctl = mx.CurriculumController(cur)
        assert ctl.update(5.0) is None and ctl.stage == 0
        assert ctl.update(12.0) == (1, (1.0, 2.0))
        # One jump can cross several thresholds; the LAST stage wins.
        ctl2 = mx.CurriculumController(cur)
        assert ctl2.update(25.0) == (2, (0.0, 1.0))
        # A later bad eval never demotes.
        assert ctl2.update(-100.0) is None and ctl2.stage == 2
        # Resume sync re-aligns (and clamps to the schedule's range).
        ctl3 = mx.CurriculumController(cur)
        ctl3.sync(1)
        assert ctl3.stage == 1 and ctl3.update(12.0) is None
        ctl3.sync(99)
        assert ctl3.stage == 2

    def test_redraw_shifts_types_with_weights(self):
        """With redraw enabled, episode ends re-draw types from the
        state-carried weights — installing one-hot weights migrates the
        whole fleet within an episode boundary."""
        env = make_mixture("cartpole,maze", redraw_types=True)
        keys = jax.random.split(jax.random.key(7), 32)
        s, _ = jax.vmap(env.reset)(keys)
        s = mx.set_fleet_weights(s, (0.0, 1.0), stage=1)
        cart = s.members[0]._replace(t=jnp.full_like(s.members[0].t, 10_000))
        maze = s.members[1]._replace(t=jnp.full_like(s.members[1].t, 10_000))
        s = s._replace(members=(cart, maze))
        out = jax.vmap(env.step)(s, jnp.zeros(32, jnp.int32))
        assert (np.asarray(out.state.type_id) == 1).all()
        assert (np.asarray(out.state.stage) == 1).all()
        assert mx.fleet_stage(out.state) == 1

    def test_curriculum_checkpoint_resume(self, tmp_path):
        """Weights + stage ride the train state through orbax, so a
        resumed run continues the schedule: leg 1 advances to stage 1
        and checkpoints; leg 2 restores, syncs the controller, and does
        NOT re-fire the crossed threshold."""
        from actor_critic_tpu.algos import a2c
        from actor_critic_tpu.utils.checkpoint import (
            Checkpointer, checkpointed_train,
        )

        env = make_mixture("cartpole,maze", redraw_types=True)
        cfg = a2c.A2CConfig(num_envs=8, rollout_steps=2, hidden=(8,))
        cur = mx.parse_curriculum("-1000:0,1", env.member_names)
        step = jax.jit(a2c.make_train_step(env, cfg), donate_argnums=0)

        def leg(iters, resume):
            ctl = mx.CurriculumController(cur)
            installs: list = []
            pending: list = []
            synced = [False]

            def tracked(s):
                if not synced[0]:
                    ctl.sync(mx.fleet_stage(s.rollout.env_state))
                    synced[0] = True
                if pending:
                    stage, w = pending.pop()
                    s = s._replace(rollout=s.rollout._replace(
                        env_state=mx.set_fleet_weights(
                            s.rollout.env_state, w, stage
                        )
                    ))
                return step(s)

            def log_fn(it, m):
                adv = ctl.update(0.0)  # stands in for the eval metric
                if adv is not None:
                    pending.append(adv)
                    installs.append(adv)

            ckpt = Checkpointer(str(tmp_path / "ck"))
            try:
                state, _ = checkpointed_train(
                    tracked, a2c.init_state(env, cfg, jax.random.key(0)),
                    iters, ckpt=ckpt, save_every=2, log_fn=log_fn,
                    resume=resume,
                )
            finally:
                ckpt.close()
            return state, installs

        state1, installs1 = leg(4, resume=False)
        assert installs1 == [(1, (0.0, 1.0))]  # crossed once, applied
        assert mx.fleet_stage(state1.rollout.env_state) == 1
        np.testing.assert_allclose(
            np.asarray(state1.rollout.env_state.weights)[0], [0.0, 1.0]
        )

        state2, installs2 = leg(8, resume=True)
        # The restored stage suppressed a replay of the stage-1 install.
        assert installs2 == []
        assert mx.fleet_stage(state2.rollout.env_state) == 1
        np.testing.assert_allclose(
            np.asarray(state2.rollout.env_state.weights)[0], [0.0, 1.0]
        )


class TestTypedEval:
    def test_typed_eval_pins_types_one_program(self):
        """reset_typed pins the eval fleet to one member (one-hot
        weights keep the pin across episode ends) and the eval program
        takes the type as a TRACED argument."""
        from actor_critic_tpu.algos import a2c

        env = make_mixture("cartpole,maze", redraw_types=True)
        keys = jax.random.split(jax.random.key(8), 16)
        for t in range(2):
            s, _ = jax.vmap(env.reset_typed, in_axes=(0, None))(
                keys, jnp.asarray(t, jnp.int32)
            )
            assert (np.asarray(s.type_id) == t).all()
        cfg = a2c.A2CConfig(num_envs=8, rollout_steps=2, hidden=(8,))
        state = a2c.init_state(env, cfg, jax.random.key(0))
        ev = jax.jit(
            mx.make_typed_eval(env, a2c.make_network(env, cfg)),
            static_argnums=(3, 4),
        )
        rets = [
            float(ev(state, jax.random.key(9), jnp.asarray(t, jnp.int32),
                     4, 16))
            for t in range(2)
        ]
        assert all(np.isfinite(r) for r in rets)
        # CartPole pays +1/step, the maze pays step costs: the matrix
        # really partitioned by type.
        assert rets[0] > 0 > rets[1]

    def test_eval_matrix_row_gauge_fields(self):
        row = mx.eval_matrix_row("cartpole", 500.0)
        assert row == {"cartpole_return": 500.0, "cartpole_solved": 1.0}
        row = mx.eval_matrix_row("acrobot", -450.0)
        assert row["acrobot_solved"] == 0.0


@pytest.mark.slow
def test_mixture_fused_a2c_smoke():
    """ISSUE 11 acceptance shape: a 4-type heterogeneous fleet steps
    and TRAINS inside one fused XLA program — finite metrics, every
    member type live in the trained fleet. Marked slow (the 4-branch
    fused train step is a ~45 s CPU compile); tier-1 keeps the
    one-program contract via test_compile_cache's 3-type acceptance
    test and the fused 2-type train in test_mixture_fused_loop_
    state_hook below."""
    from actor_critic_tpu.algos import a2c

    env = make_mixture("cartpole,pendulum,acrobot,maze", randomize=0.2)
    cfg = a2c.A2CConfig(num_envs=64, rollout_steps=4, hidden=(16,))
    state, metrics = a2c.train(env, cfg, num_iterations=3, seed=0)
    assert int(state.update_step) == 3
    for k, v in metrics.items():
        assert np.isfinite(float(v)), (k, v)
    types = np.asarray(state.rollout.env_state.type_id)
    assert set(np.unique(types)) == {0, 1, 2, 3}


def test_mixture_fused_loop_state_hook():
    """host_loop.fused_train_loop's state_hook is the curriculum's
    between-dispatch seam: installing one-hot weights mid-run migrates
    the training fleet's types without retracing the step."""
    from actor_critic_tpu.algos import a2c

    env = make_mixture("cartpole,maze", redraw_types=True)
    # maze episodes end fast (step cost truncation at 8*size), but not
    # within 6 tiny iterations reliably — force migration by hooking
    # BOTH weights and member clocks.
    cfg = a2c.A2CConfig(num_envs=16, rollout_steps=2, hidden=(8,))

    def hook(it, state):
        if it != 2:
            return state
        es = mx.set_fleet_weights(state.rollout.env_state, (0.0, 1.0), 1)
        cart = es.members[0]._replace(t=jnp.full_like(es.members[0].t, 9_999))
        maze = es.members[1]._replace(t=jnp.full_like(es.members[1].t, 9_999))
        es = es._replace(members=(cart, maze))
        return state._replace(rollout=state.rollout._replace(env_state=es))

    state, _ = a2c.train(
        env, cfg, num_iterations=4, seed=0, state_hook=hook
    )
    assert (np.asarray(state.rollout.env_state.type_id) == 1).all()
    assert mx.fleet_stage(state.rollout.env_state) == 1
