"""Sharded multi-process host env pool + block buffers (ISSUE 2).

Contracts:
- `HostEnvPool(workers=W)` reproduces the `workers=1` (SyncVectorEnv)
  pool EXACTLY at fixed seeds: obs, rewards, dones, final_obs and the
  RunningMeanStd normalizer state, including uneven shards (E % W != 0).
- A worker crash (env exception) surfaces as a raised RuntimeError from
  the next barrier — never a hang — and `close()` after a crash returns.
- Checkpoint/resume works with `workers > 1` (normalizer stats restore
  through the same `get_state`/`set_state` path; training continues).
- `BlockBuffers` double-buffers: block N's arrays stay intact while
  block N+1 is recorded, and buffers are REUSED (no per-block allocs).
- The sharded pool feeds telemetry: per-worker `env_step_worker` block
  spans via host_collect, and a pool-utilization gauge in the sampler.
"""

import json

import numpy as np
import pytest

gym = pytest.importorskip("gymnasium")

from actor_critic_tpu.envs.host_pool import HostEnvPool
from actor_critic_tpu.envs.shard_pool import shard_bounds

SLEEP_PAD = "actor_critic_tpu.envs.sleep_pad:SleepPad-v0"


def _rollout(pool, steps, seed):
    rng = np.random.default_rng(seed)
    obs = pool.reset()
    frames = [("reset", obs)]
    for _ in range(steps):
        acts = rng.integers(0, 2, pool.num_envs).astype(np.int64)
        out = pool.step(acts)
        frames.append(
            (out.obs, out.reward, out.done, out.terminated, out.final_obs)
        )
        obs = out.obs
    return frames


def test_shard_bounds_cover_and_balance():
    assert shard_bounds(8, 4) == [(0, 2), (2, 4), (4, 6), (6, 8)]
    assert shard_bounds(5, 2) == [(0, 3), (3, 5)]
    assert shard_bounds(3, 3) == [(0, 1), (1, 2), (2, 3)]


def test_workers_validation():
    with pytest.raises(ValueError, match="workers"):
        HostEnvPool("CartPole-v1", num_envs=2, workers=0)
    with pytest.raises(ValueError, match="workers"):
        HostEnvPool("CartPole-v1", num_envs=2, workers=3)
    with pytest.raises(ValueError, match="gym backend"):
        HostEnvPool("CartPole-v1", num_envs=2, backend="native", workers=2)


def test_sharded_matches_sync_bit_for_bit():
    """E=5 over W=2 (uneven shards) must equal the SyncVectorEnv pool
    exactly — trajectories AND normalization statistics — at fixed
    seeds; global per-env seeding makes shard layout invisible."""
    E, K = 5, 120
    sync = HostEnvPool("CartPole-v1", E, seed=3)
    shard = HostEnvPool("CartPole-v1", E, seed=3, workers=2)
    try:
        fa = _rollout(sync, K, seed=7)
        fb = _rollout(shard, K, seed=7)
        for a, b in zip(fa, fb):
            for xa, xb in zip(a, b):
                if isinstance(xa, str):
                    continue
                np.testing.assert_array_equal(xa, xb)
        # RunningMeanStd state identical (obs + reward normalizers).
        np.testing.assert_array_equal(sync.obs_rms.mean, shard.obs_rms.mean)
        np.testing.assert_array_equal(sync.obs_rms.var, shard.obs_rms.var)
        assert sync.obs_rms.count == shard.obs_rms.count
        np.testing.assert_array_equal(sync.ret_rms.mean, shard.ret_rms.mean)
        np.testing.assert_array_equal(sync.ret_rms.var, shard.ret_rms.var)
        assert sync.ret_rms.count == shard.ret_rms.count
    finally:
        sync.close()
        shard.close()


def test_worker_crash_raises_not_hangs():
    """An env exception inside a worker must surface as a RuntimeError at
    the pending barrier (the watchdog-free failure contract); close()
    afterwards must return, not hang."""
    pool = HostEnvPool(
        SLEEP_PAD, 4, seed=0, workers=2,
        normalize_obs=False, normalize_reward=False,
        env_kwargs={"crash_at_step": 3},
    )
    pool.reset()
    acts = np.zeros(4, np.int64)
    with pytest.raises(RuntimeError, match="worker"):
        for _ in range(10):
            pool.step(acts)
    pool.close()


def test_sharded_pool_validation_failure_closes_workers():
    """A post-construction validation failure (scale_actions on a
    discrete env) must tear the live backend down: no orphan worker
    processes, no gauge bound to an unreachable pool."""
    import multiprocessing as mp

    from actor_critic_tpu.telemetry.sampler import sample_row

    with pytest.raises(ValueError, match="finite continuous"):
        HostEnvPool("CartPole-v1", num_envs=2, workers=2, scale_actions=True)
    assert "host_pool" not in sample_row()
    leftovers = [
        p for p in mp.active_children() if p.name.startswith("env-shard")
    ]
    assert leftovers == [], leftovers


def test_ppo_host_resume_with_sharded_pool(tmp_path):
    """Checkpoint/resume with workers>1: same contract as the workers=1
    resume tests (device state + normalizer stats restore; training
    continues from the saved iteration)."""
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.utils.checkpoint import Checkpointer

    cfg = ppo.PPOConfig(
        num_envs=2, rollout_steps=8, epochs=1, num_minibatches=1, hidden=(16,)
    )
    pool = HostEnvPool("CartPole-v1", num_envs=2, seed=0, workers=2)
    with Checkpointer(tmp_path / "ck") as ck:
        ppo.train_host(
            pool, cfg, num_iterations=2, seed=0, log_every=0,
            ckpt=ck, save_every=1,
        )
        ck.wait()
        saved_count = pool.obs_rms.count
    pool.close()

    pool2 = HostEnvPool("CartPole-v1", num_envs=2, seed=0, workers=2)
    with Checkpointer(tmp_path / "ck") as ck:
        _, _, history = ppo.train_host(
            pool2, cfg, num_iterations=4, seed=0, log_every=1,
            ckpt=ck, save_every=1, resume=True,
        )
        assert ck.latest_step() == 4
    # Only iterations 3..4 ran, and the restored stats carried over
    # (resume pushes obs_rms back through pool.set_state, then training
    # keeps accumulating past the saved count).
    assert [it for it, _ in history] == [3, 4]
    assert pool2.obs_rms.count > saved_count
    pool2.close()


def test_sharded_pool_telemetry(tmp_path):
    """The worker→parent relay must land one env_step_worker span per
    worker per BATCH STEP in spans.jsonl, carrying each worker's REAL
    pid (its own Perfetto lane, ≠ the parent's) and a process_name
    metadata label; the sampler row must carry the pool gauge while the
    pool lives (and drop it after close)."""
    import os

    from actor_critic_tpu import telemetry
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.telemetry.sampler import sample_row

    cfg = ppo.PPOConfig(
        num_envs=2, rollout_steps=4, epochs=1, num_minibatches=1, hidden=(16,)
    )
    pool = HostEnvPool("CartPole-v1", num_envs=2, seed=0, workers=2)
    with telemetry.TelemetrySession(tmp_path, sample_resources=False):
        ppo.train_host(pool, cfg, num_iterations=2, seed=0, log_every=0)
        gauge = sample_row().get("host_pool")
    assert gauge is not None
    assert gauge["workers"] == 2 and gauge["num_envs"] == 2
    assert 0.0 <= gauge["utilization"] <= 1.0
    assert gauge["env_steps"] >= 2 * cfg.rollout_steps * cfg.num_envs
    pool.close()
    assert "host_pool" not in sample_row()

    with open(tmp_path / "spans.jsonl") as f:
        events = [json.loads(line) for line in f if line.strip()]
    spans = [
        e for e in events
        if e.get("name") == "env_step_worker" and e["ph"] == "X"
    ]
    assert {e["args"]["worker"] for e in spans} == {0, 1}
    # Relayed records: one span per worker per batch step, REAL pids.
    assert len(spans) == 2 * 2 * cfg.rollout_steps, len(spans)
    pids = {e["pid"] for e in spans}
    assert len(pids) == 2 and os.getpid() not in pids, pids
    assert all(e["dur"] >= 0 and e["ts"] >= 0 for e in spans)
    # Each worker lane is labeled for Perfetto.
    labels = {
        e["pid"]: e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert {labels.get(p) for p in pids} == {"env-shard-0", "env-shard-1"}


def test_block_buffers_double_buffer_and_reuse():
    from actor_critic_tpu.algos.host_loop import BlockBuffers

    bufs = BlockBuffers(3)
    bufs.begin_block()
    for t in range(3):
        bufs.record(t, "x", np.full(2, t, np.float32))
    b1 = bufs.block()["x"]
    np.testing.assert_array_equal(b1[:, 0], [0, 1, 2])

    bufs.begin_block()
    for t in range(3):
        bufs.record(t, "x", np.full(2, 10 + t, np.float32))
    b2 = bufs.block()["x"]
    assert b1 is not b2
    # Block 1's arrays are INTACT while block 2 is live — the property
    # that lets block 1's device transfer overlap block 2's collection.
    np.testing.assert_array_equal(b1[:, 0], [0, 1, 2])
    np.testing.assert_array_equal(b2[:, 0], [10, 11, 12])

    bufs.begin_block()
    for t in range(3):
        bufs.record(t, "x", np.full(2, 20 + t, np.float32))
    # Steady state reuses block 1's storage: no per-block allocation.
    assert bufs.block()["x"] is b1
    np.testing.assert_array_equal(b1[:, 0], [20, 21, 22])


def test_block_buffers_never_leak_stale_keys():
    """A key recorded in an earlier block but not the current one must
    be absent from block() — not silently served two blocks stale."""
    from actor_critic_tpu.algos.host_loop import BlockBuffers

    bufs = BlockBuffers(2)
    bufs.begin_block()
    for t in range(2):
        bufs.record(t, "x", np.zeros(1, np.float32))
        bufs.record(t, "aux", np.ones(1, np.float32))
    assert set(bufs.block()) == {"x", "aux"}
    bufs.begin_block()
    bufs.begin_block()  # back on the buffer set that once held "aux"
    for t in range(2):
        bufs.record(t, "x", np.full(1, 5.0, np.float32))
    assert set(bufs.block()) == {"x"}


def test_host_collect_block_matches_legacy_stacking():
    """The preallocated-buffer path must produce the exact [K, E, ...]
    block the old list-append+np.stack path produced, extras included."""
    from actor_critic_tpu.algos.host_loop import (
        BlockBuffers,
        EpisodeTracker,
        host_collect,
    )

    def run(buffers):
        pool = HostEnvPool("CartPole-v1", num_envs=3, seed=5)
        rng = np.random.default_rng(11)

        def act(obs):
            a = rng.integers(0, 2, 3).astype(np.int64)
            return a, {"aux": obs.sum(axis=-1)}

        obs, block = host_collect(
            pool, pool.reset(), 6, act, EpisodeTracker(3), buffers=buffers
        )
        pool.close()
        return obs, block

    obs_a, block_a = run(None)                  # per-call buffers
    obs_b, block_b = run(BlockBuffers(6))       # loop-lived buffers
    np.testing.assert_array_equal(obs_a, obs_b)
    assert set(block_a) == {
        "obs", "action", "aux", "reward", "done", "terminated", "final_obs"
    }
    for k in block_a:
        assert block_a[k].shape[0] == 6, k
        np.testing.assert_array_equal(block_a[k], block_b[k])
