"""Sharded-state checkpoint/restore (VERDICT round 4, missing #4).

Every other checkpoint test runs single-device; a real multi-chip
deployment's first failure mode is saving/restoring a dp-SHARDED
`OffPolicyState` — the replay ring split over the mesh (SURVEY.md
§5.3–5.4). This trains a dp-sharded TD3 on the fake 8-device CPU mesh,
orbax-saves, restores restart-style into a freshly distributed template,
and asserts (a) the restored ring is still dp-sharded with bitwise-equal
shard contents, and (b) continuing from the restore reproduces the
uninterrupted run's metrics and params bitwise.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from actor_critic_tpu.algos import ddpg
from actor_critic_tpu.envs import make_point_mass
from actor_critic_tpu.parallel import (
    DP_AXIS,
    distribute_state,
    make_dp_train_step,
    make_mesh,
    offpolicy_state_specs,
)
from actor_critic_tpu.utils.checkpoint import Checkpointer

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (fake) devices"
)


def _cfg():
    return ddpg.td3_config(
        num_envs=16, steps_per_iter=4, updates_per_iter=2,
        buffer_capacity=512, batch_size=8, warmup_steps=0, hidden=(16,),
    )


def _metrics_np(m):
    return {k: np.asarray(v) for k, v in m.items()}


def test_sharded_offpolicy_checkpoint_roundtrip(tmp_path):
    env = make_point_mass()
    cfg = _cfg()
    mesh = make_mesh()
    specs = offpolicy_state_specs()
    step = make_dp_train_step(
        ddpg.make_train_step(env, cfg, axis_name=DP_AXIS), mesh, specs
    )

    state = distribute_state(ddpg.init_state(env, cfg, jax.random.key(0)), mesh, specs)
    for _ in range(3):
        state, _ = step(state)
    jax.block_until_ready(state)

    ckpt = Checkpointer(tmp_path)
    assert ckpt.save(3, state, force=True)
    ckpt.wait()

    # Snapshot save-time values BEFORE the donating continuation steps
    # destroy the buffers.
    saved_ring_obs = np.asarray(state.learner.replay.storage.obs)
    saved_actor_leaf = np.asarray(
        jax.tree.leaves(state.learner.actor_params)[0]
    )

    # Arm A: uninterrupted continuation.
    cont_metrics = []
    for _ in range(2):
        state, m = step(state)
        cont_metrics.append(_metrics_np(m))
    jax.block_until_ready(state)

    # Arm B: restart-style restore into a FRESHLY DISTRIBUTED template
    # (new process semantics: nothing survives but the checkpoint).
    template = distribute_state(
        ddpg.init_state(env, cfg, jax.random.key(0)), mesh, specs
    )
    restored = ckpt.restore(template, 3)
    ckpt.close()

    # (a) the restored ring is still dp-sharded, contents bitwise equal.
    ring = restored.learner.replay.storage.obs
    assert ring.sharding.spec == P(DP_AXIS), ring.sharding
    np.testing.assert_array_equal(np.asarray(ring), saved_ring_obs)
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(restored.learner.actor_params)[0]),
        saved_actor_leaf,
    )
    # Params replicated (every device bitwise identical), as distributed.
    leaf = jax.tree.leaves(restored.learner.actor_params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)

    # (b) bitwise metric + param continuation: the restored arm must be
    # indistinguishable from never having restarted.
    for i in range(2):
        restored, m = step(restored)
        rm = _metrics_np(m)
        for k, v in cont_metrics[i].items():
            np.testing.assert_array_equal(v, rm[k], err_msg=f"step {i} {k}")
    jax.block_until_ready(restored)
    for a, b in zip(
        jax.tree.leaves(state.learner.critic_params),
        jax.tree.leaves(restored.learner.critic_params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
