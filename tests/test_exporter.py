"""Live run introspection (telemetry/exporter.py + profiler.py, ISSUE 3).

Contracts, all against an EPHEMERAL port (serve_port=0):
- `/metrics` is valid Prometheus text exposing steps/s, the recompile
  counter, registered sampler gauges, and the last observe() row;
- `/healthz` reports open span + watchdog staleness, and flips to 503
  exactly when an armed watchdog is past timeout outside grace;
- `/profile?iters=N` (and SIGUSR2) arm a windowed jax.profiler capture
  that the training-loop tick starts/stops, leaving a trace directory
  under the telemetry dir plus profile_start/profile_done events;
- the compile listener turns XLA compilations into structured `compile`
  events carrying the abstract argument signature, so a recompile names
  the shape/dtype that changed;
- `train.py --telemetry-port` refuses to run without --telemetry-dir,
  and (slow) a live CPU run answers /metrics + /healthz mid-training.
"""

import json
import os
import re
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from actor_critic_tpu import telemetry
from actor_critic_tpu.telemetry.exporter import render_metrics
from actor_critic_tpu.utils import watchdog as watchdog_mod

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? \S+$"
)


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _session(tmp_path, **kw):
    kw.setdefault("sample_resources", False)
    kw.setdefault("serve_port", 0)
    return telemetry.TelemetrySession(tmp_path, **kw)


# ---------------------------------------------------------------- /metrics


def test_metrics_is_valid_prometheus_text_with_rates(tmp_path):
    with _session(tmp_path) as s:
        telemetry.observe(1, {"loss": 0.5, "env_steps": 100})
        time.sleep(0.02)
        telemetry.observe(3, {"loss": 0.25, "env_steps": 300})
        status, body = _get(s.exporter.url + "/metrics")
    assert status == 200
    samples = {}
    for line in body.splitlines():
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE ")), line
            continue
        assert _PROM_LINE.match(line), line
        name_part, value = line.rsplit(" ", 1)
        samples[name_part] = float(value)  # every sample parses numeric
    assert samples["actor_critic_up"] == 1
    assert samples["actor_critic_xla_recompiles_total"] >= 0
    assert samples["actor_critic_rss_bytes"] > 0
    # steps/s + iters/s from the two observe() rows
    assert samples["actor_critic_env_steps_per_s"] > 0
    assert samples["actor_critic_iters_per_s"] > 0
    # the last training row rides along, labeled per metric
    assert samples['actor_critic_train_metric{metric="loss"}'] == 0.25
    assert samples["actor_critic_train_iteration"] == 3


def test_metrics_includes_registered_gauges(tmp_path):
    from actor_critic_tpu.telemetry import sampler

    key = sampler.register_gauge(
        "host_pool", lambda: {"utilization": 0.75, "workers": 2}
    )
    try:
        with _session(tmp_path) as s:
            body = render_metrics(s)  # pure render, no socket needed
    finally:
        sampler.unregister_gauge(key)
    assert "actor_critic_host_pool_utilization 0.75" in body
    assert "actor_critic_host_pool_workers 2" in body


def test_metrics_drops_nan_training_values(tmp_path):
    with _session(tmp_path) as s:
        telemetry.observe(1, {"loss": float("nan"), "ok": 1.0})
        body = render_metrics(s)
    assert 'metric="ok"' in body
    assert 'metric="loss"' not in body  # NaN would break scrapers


# ---------------------------------------------------------------- /healthz


def test_healthz_reports_open_span_and_ok(tmp_path):
    with _session(tmp_path) as s:
        with telemetry.span("update", it=5):
            status, body = _get(s.exporter.url + "/healthz")
    h = json.loads(body)
    assert status == 200 and h["status"] == "ok"
    assert h["open_span"]["name"] == "update"
    assert h["open_span"]["open_s"] >= 0
    assert h["profiler"]["state"] == "idle"


def test_healthz_503_when_watchdog_stalled(tmp_path):
    """An armed watchdog past its timeout outside grace must flip
    /healthz to 503/stalled — the condition tpu_watch-style probes key
    on. The watchdog is injected un-started (its firing thread would
    os._exit the test runner)."""
    w = watchdog_mod.StallWatchdog(timeout_s=1.0, startup_grace_s=0.0)
    now = time.monotonic()
    w._last = now - 10.0
    w._grace_until = now - 5.0
    watchdog_mod._ACTIVE.append(w)
    try:
        with _session(tmp_path) as s:
            url = s.exporter.url + "/healthz"
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url, timeout=10)
            assert ei.value.code == 503
            h = json.loads(ei.value.read())
            assert h["status"] == "stalled"
            assert h["watchdog"]["staleness_s"] > h["watchdog"]["timeout_s"]
            # a heartbeat landing brings it back to 200
            w.touch()
            status, body = _get(url)
            assert status == 200 and json.loads(body)["status"] == "ok"
    finally:
        watchdog_mod._ACTIVE.remove(w)


def test_unknown_route_404(tmp_path):
    with _session(tmp_path) as s:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(s.exporter.url + "/nope", timeout=10)
        assert ei.value.code == 404


# ---------------------------------------------------------------- /profile


def test_profile_endpoint_captures_a_window(tmp_path):
    import jax
    import jax.numpy as jnp

    with _session(tmp_path) as s:
        status, body = _get(s.exporter.url + "/profile?iters=2")
        assert status == 202 and json.loads(body)["state"] == "armed"
        f = jax.jit(lambda x: x * 2.0)
        telemetry.profiler_tick()  # capture starts here
        assert s.profiler.status()["state"] == "active"
        jax.block_until_ready(f(jnp.ones(4)))
        telemetry.profiler_tick()
        telemetry.profiler_tick()  # window of 2 ends: capture stops
        assert s.profiler.status() == {"state": "idle", "captures": 1}
    # trace directory under the telemetry dir, named by the events
    events = _read_jsonl(tmp_path / "events.jsonl")
    start = [e for e in events if e["kind"] == "profile_start"]
    done = [e for e in events if e["kind"] == "profile_done"]
    assert len(start) == 1 and len(done) == 1
    assert start[0]["iters"] == 2
    path = done[0]["path"]
    assert path.startswith(str(tmp_path)) and os.path.isdir(path)
    assert any(os.scandir(path)), "profiler wrote an empty directory"
    # the capture window also lands as a phase span
    names = [
        e["name"] for e in _read_jsonl(tmp_path / "spans.jsonl")
        if e.get("ph") == "X"
    ]
    assert "profile" in names


def test_profile_rejects_bad_iters(tmp_path):
    with _session(tmp_path) as s:
        for q in ("iters=0", "iters=abc"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    s.exporter.url + "/profile?" + q, timeout=10
                )
            assert ei.value.code == 400


def test_arming_twice_keeps_first_window(tmp_path):
    with _session(tmp_path, serve_port=None) as s:
        assert s.profiler.arm(3)["iters"] == 3
        assert s.profiler.arm(50)["iters"] == 3  # no-op report, no error
        s.profiler._armed_iters = 0  # disarm without starting a capture


@pytest.mark.skipif(
    not hasattr(signal, "SIGUSR2"), reason="no SIGUSR2 on this platform"
)
def test_sigusr2_arms_capture(tmp_path):
    from actor_critic_tpu.telemetry.profiler import install_sigusr2

    assert install_sigusr2(iters=4)
    try:
        with _session(tmp_path, serve_port=None) as s:
            os.kill(os.getpid(), signal.SIGUSR2)
            deadline = time.monotonic() + 5.0
            while (
                s.profiler.status()["state"] != "armed"
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            assert s.profiler.status()["state"] == "armed"
            assert s.profiler.status()["iters"] == 4
            # disarm without starting a capture (no jax work here)
            s.profiler._arm_seen = s.profiler._arm_requests
    finally:
        signal.signal(signal.SIGUSR2, signal.SIG_DFL)


# ------------------------------------------------------- compile listener


def test_compile_events_name_the_changed_signature(tmp_path):
    """Two dispatches of one jitted function at different shapes must
    produce `compile` events whose abstract argument signatures DIFFER —
    the recompile-attribution contract."""
    import jax
    import jax.numpy as jnp

    def distinctly_named_fn(x):
        return x * 3.0

    f = jax.jit(distinctly_named_fn)
    with _session(tmp_path, serve_port=None):
        jax.block_until_ready(f(jnp.ones(7)))
        jax.block_until_ready(f(jnp.ones(13)))  # shape change → recompile
    comps = [
        e for e in _read_jsonl(tmp_path / "events.jsonl")
        if e["kind"] == "compile" and "distinctly_named_fn" in e.get("name", "")
    ]
    assert len(comps) == 2, [e.get("name") for e in comps]
    sigs = {e.get("signature") for e in comps}
    assert len(sigs) == 2 and all(s for s in sigs), sigs
    assert "7" in "".join(sigs) and "13" in "".join(sigs)
    assert all(e["compile_s"] >= 0 for e in comps)


# ------------------------------------------------------------- train.py


def test_cli_telemetry_port_requires_dir():
    import train as train_cli

    with pytest.raises(SystemExit, match="telemetry-dir"):
        train_cli.main(["--preset", "a2c_cartpole", "--telemetry-port", "0"])
    with pytest.raises(SystemExit, match="sample-s"):
        train_cli.main(
            ["--preset", "a2c_cartpole", "--telemetry-dir", "/tmp/x",
             "--telemetry-sample-s", "0"]
        )


@pytest.mark.slow
def test_cli_live_introspection_end_to_end(tmp_path):
    """A real CPU train.py run with --telemetry-port 0 must answer
    /metrics (steps/s + recompile count) and /healthz while training."""
    tel = tmp_path / "tel"
    cmd = [
        sys.executable, "train.py",
        "--algo", "a2c", "--env", "jax:two_state",
        "--iterations", "30000", "--log-every", "5", "--quiet",
        "--set", "num_envs=8", "--set", "rollout_steps=4",
        "--set", "hidden=16",
        "--metrics", str(tmp_path / "m.jsonl"),
        "--telemetry-dir", str(tel), "--telemetry-port", "0",
    ]
    env = {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
           "PATH": "/usr/bin:/bin"}
    env.update({k: v for k, v in os.environ.items() if k not in env})
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, bufsize=1, cwd="/root/repo", env=env,
    )
    try:
        url = None
        for line in proc.stdout:
            m = re.search(r"telemetry exporter: (http://\S+)/metrics", line)
            if m:
                url = m.group(1)
                break
        assert url, "exporter URL never printed"
        # Wait for training rows (first compile dominates), then scrape.
        deadline = time.monotonic() + 120
        body = ""
        while time.monotonic() < deadline:
            _, body = _get(url + "/metrics")
            if "actor_critic_env_steps_per_s" in body:
                break
            time.sleep(1.0)
        assert "actor_critic_env_steps_per_s" in body, body[-2000:]
        assert "actor_critic_xla_recompiles_total" in body
        status, h = _get(url + "/healthz")
        assert status == 200 and json.loads(h)["status"] == "ok"
    finally:
        proc.terminate()
        proc.wait(timeout=30)
    comps = [
        e for e in _read_jsonl(tel / "events.jsonl")
        if e["kind"] == "compile"
    ]
    assert comps, "no compile events from a fresh jit process"


def test_ephemeral_port_reported_on_session_object(tmp_path):
    """ISSUE 10 satellite: serve_port=0 binds an OS-assigned port, and
    the ACTUAL bound port is readable off the session (exporter_port)
    and recorded in the exporter_start event — scripts and CI read it
    instead of racing for a fixed port."""
    with _session(tmp_path) as s:
        port = s.exporter_port
        assert port not in (None, 0)
        assert s.exporter.url.endswith(f":{port}")
        status, _ = _get(s.exporter.url + "/healthz")
        assert status == 200
    events = _read_jsonl(os.path.join(tmp_path, "events.jsonl"))
    starts = [e for e in events if e.get("kind") == "exporter_start"]
    assert starts and starts[0]["port"] == port
    # No exporter -> None, not an attribute error.
    with _session(tmp_path, serve_port=None) as s2:
        assert s2.exporter_port is None


# ------------------------------------------------------------- ISSUE 16


def test_closed_session_renders_tombstone(tmp_path):
    """A scraper hitting a session that already close()d must read
    `up 0` — down, not frozen: stale gauges from a dead process are
    indistinguishable from a healthy flatline."""
    with _session(tmp_path) as s:
        telemetry.observe(1, {"loss": 0.5})
        live = render_metrics(s)
        assert "actor_critic_up 1" in live and "loss" in live
    dead = render_metrics(s)  # the with-block close()d it
    assert dead.strip().splitlines()[-1] == "actor_critic_up 0"
    assert "loss" not in dead  # no stale training row
    assert len(dead.strip().splitlines()) <= 3


def test_histogram_gauge_renders_prometheus_family(tmp_path):
    """A histogram snapshot inside a registered gauge row renders as a
    `_bucket/_sum/_count` family (policy-labeled), not as a skipped
    non-numeric value."""
    from actor_critic_tpu.telemetry import histo, sampler

    h = histo.Histogram((1.0, 10.0))
    h.observe_many([0.5, 5.0, 50.0])
    snap = h.snapshot(labels={"policy": "champ"})
    snap["metric"] = "latency_ms"
    key = sampler.register_gauge(
        "serving", lambda: {
            "requests_total": 3, "latency_ms_hist_champ": snap,
        },
    )
    try:
        with _session(tmp_path) as s:
            body = render_metrics(s)
    finally:
        sampler.unregister_gauge(key)
    fam = "actor_critic_serving_latency_ms"
    assert f'{fam}_bucket{{policy="champ",le="1"}} 1' in body
    assert f'{fam}_bucket{{policy="champ",le="+Inf"}} 3' in body
    assert f'{fam}_count{{policy="champ"}} 3' in body
    assert "actor_critic_serving_requests_total 3" in body
    # every line still parses as Prometheus text
    for line in body.splitlines():
        if line and not line.startswith("#"):
            assert _PROM_LINE.match(line), line


def test_concurrent_scrape_during_hot_swap_and_sampler_tick(tmp_path):
    """/metrics scraped continuously while (a) the policy store
    hot-swaps under live traffic and (b) the resource sampler ticks at
    high cadence: every scrape must be complete, parseable Prometheus
    text with monotone histogram counts — never a torn view or a 500."""
    import numpy as np

    from actor_critic_tpu import serving

    class _Eng:
        max_rows = 8

        def prepare_params(self, params):
            return {k: np.array(v) for k, v in params.items()}

        def act(self, params, obs):
            return np.asarray(obs)[:, 0] * params["scale"][0]

    store = serving.PolicyStore()
    store.register(
        "default", _Eng(), {"scale": np.ones(1, np.float32)}, slo_ms=50.0
    )
    session = telemetry.TelemetrySession(
        tmp_path, resource_interval_s=0.02, serve_port=0
    )
    gw = serving.ServeGateway(store, port=0, session=session)
    stop = None
    try:
        import threading

        stop = threading.Event()
        errors: list = []

        def traffic():
            i = 0
            while not stop.is_set():
                body = json.dumps(
                    {"obs": [[float(i + 1), 0.0]]}
                ).encode()
                req = urllib.request.Request(
                    gw.url + "/v1/act", data=body,
                    headers={"Content-Type": "application/json"},
                )
                try:
                    urllib.request.urlopen(req, timeout=10).read()
                except Exception as e:  # noqa: BLE001
                    errors.append(("traffic", repr(e)))
                    return
                i += 1

        def swapper():
            v = 0
            while not stop.is_set():
                v += 1
                store.swap(
                    "default",
                    {"scale": np.full(1, float(v + 1), np.float32)},
                    version=v,
                )
                time.sleep(0.002)

        threads = [
            threading.Thread(target=traffic),
            threading.Thread(target=swapper),
        ]
        for t in threads:
            t.start()
        last_count = 0.0
        deadline = time.monotonic() + 2.0
        scrapes = 0
        count_re = re.compile(
            r'actor_critic_serving_latency_ms_count\{policy="default"\} '
            r"(\S+)"
        )
        while time.monotonic() < deadline:
            status, text = _get(session.exporter.url + "/metrics")
            assert status == 200
            for line in text.splitlines():
                if line and not line.startswith("#"):
                    assert _PROM_LINE.match(line), line
            m = count_re.search(text)
            if m:
                count = float(m.group(1))
                assert count >= last_count  # counters never run backwards
                last_count = count
            scrapes += 1
        stop.set()
        for t in threads:
            t.join(10)
        assert not errors, errors[:3]
        assert scrapes >= 10 and last_count > 0
    finally:
        if stop is not None:
            stop.set()
        gw.close()
        session.close()


def test_validate_bind_refuses_non_loopback_without_distributed():
    from actor_critic_tpu.telemetry.exporter import validate_bind

    for host in ("127.0.0.1", "localhost", "::1"):
        validate_bind(host)  # loopback always fine
    with pytest.raises(ValueError, match="distributed"):
        validate_bind("0.0.0.0")
    with pytest.raises(ValueError):
        validate_bind("10.0.0.7")
    validate_bind("0.0.0.0", distributed=True)  # fleet scrape path


def test_cli_telemetry_bind_refused_without_distributed():
    import train as train_cli

    with pytest.raises(SystemExit, match="loopback"):
        train_cli.main(
            ["--preset", "a2c_cartpole", "--telemetry-dir", "/tmp/x",
             "--telemetry-bind", "0.0.0.0"]
        )
