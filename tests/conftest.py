"""Test configuration: run the suite on a fake 8-device CPU mesh.

Per SURVEY.md §4 ("Distributed tests without a cluster"): the axon plugin
exposes a single TPU chip, so tests validate sharding/collective semantics
with `--xla_force_host_platform_device_count=8` CPU devices.

Environment quirk (verified in-session): this container's
`sitecustomize.py` (PYTHONPATH=/root/.axon_site) imports jax and registers
the axon TPU PJRT plugin at *interpreter startup*, and a fresh process
started with `JAX_PLATFORMS=cpu` deadlocks inside that registration. So
instead of env vars, we flip the already-imported jax to CPU via
`jax.config` — backends are created lazily, so as long as this runs before
the first computation (conftest import time), the forced device count
takes effect.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def new_compile_records(c0: int) -> list:
    """Compile records since event-count snapshot `c0`
    (`profiler.compile_event_count()`). The record ring is capped, so in
    a full-suite run len(records) sits at capacity and slicing by list
    length silently returns [] — index back from the MONOTONIC counter
    instead."""
    from actor_critic_tpu.telemetry import profiler

    delta = profiler.compile_event_count() - c0
    return profiler.compile_records()[-delta:] if delta else []
