"""Checkpoint/resume tests (SURVEY.md §4-§5.3): exact-state roundtrip,
kill-resume equivalence with an uninterrupted run, and retention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.algos import a2c
from actor_critic_tpu.envs import make_two_state_mdp
from actor_critic_tpu.utils.checkpoint import (
    Checkpointer,
    checkpointed_train,
    resume_or_init,
)


def _setup():
    env = make_two_state_mdp()
    cfg = a2c.A2CConfig(num_envs=8, rollout_steps=4, hidden=(16,))
    state = a2c.init_state(env, cfg, jax.random.key(0))
    step = jax.jit(a2c.make_train_step(env, cfg))
    return env, cfg, state, step


def _assert_states_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(x) if jnp.issubdtype(x.dtype, jax.dtypes.prng_key) else x),
            np.asarray(jax.random.key_data(y) if jnp.issubdtype(y.dtype, jax.dtypes.prng_key) else y),
        )


def test_roundtrip_exact(tmp_path):
    _, _, state, step = _setup()
    state, _ = step(state)
    with Checkpointer(tmp_path / "ckpt") as ck:
        ck.save(1, state, force=True)
        ck.wait()
        restored = ck.restore(state)
    _assert_states_equal(state, restored)


def test_kill_resume_matches_uninterrupted(tmp_path):
    """Run 3 steps, 'die', restore, run 3 more == 6 uninterrupted steps."""
    _, _, state0, step = _setup()

    full = state0
    for _ in range(6):
        full, full_metrics = step(full)

    half = state0
    for _ in range(3):
        half, _ = step(half)
    with Checkpointer(tmp_path / "ckpt") as ck:
        ck.save(3, half, force=True)
        ck.wait()
        # "New process": restore into a freshly-initialized template.
        _, _, fresh, _ = _setup()
        resumed = ck.restore(fresh, 3)
    for _ in range(3):
        resumed, resumed_metrics = step(resumed)

    _assert_states_equal(full, resumed)
    for k in full_metrics:
        np.testing.assert_array_equal(
            np.asarray(full_metrics[k]), np.asarray(resumed_metrics[k])
        )


def test_checkpointed_train_resumes(tmp_path):
    """checkpointed_train killed mid-run completes to the same final state."""
    _, _, state0, step = _setup()

    with Checkpointer(tmp_path / "a") as ck:
        ref, _ = checkpointed_train(step, state0, 8, ck, save_every=3)

    # Interrupted: first call only gets through 4 iterations ("kill" = we
    # stop calling); checkpoint exists at 3. Second call resumes at 3.
    with Checkpointer(tmp_path / "b") as ck:
        s = state0
        for it in range(1, 5):
            s, _ = step(s)
            if it % 3 == 0:
                jax.block_until_ready(s)
                ck.save(it, s, force=True)
        ck.wait()
        assert ck.latest_step() == 3
        resumed, _ = checkpointed_train(step, state0, 8, ck, save_every=3)

    _assert_states_equal(ref, resumed)


def test_resume_or_init_fresh(tmp_path):
    _, _, state0, _ = _setup()
    with Checkpointer(tmp_path / "empty") as ck:
        state, done = resume_or_init(ck, state0)
    assert done == 0
    _assert_states_equal(state, state0)


def test_retention_and_latest(tmp_path):
    _, _, state, step = _setup()
    with Checkpointer(tmp_path / "ckpt", max_to_keep=2) as ck:
        for it in (1, 2, 3):
            state, _ = step(state)
            jax.block_until_ready(state)
            ck.save(it, state, force=True)
        ck.wait()
        assert ck.latest_step() == 3
        kept = ck.all_steps()
    assert 3 in kept and len(kept) <= 2


def test_restore_legacy_bare_layout(tmp_path):
    """Checkpoints written by the pre-metrics bare-StandardSave layout
    must still restore (and restore_metrics must return {})."""
    import orbax.checkpoint as ocp

    from actor_critic_tpu.utils.checkpoint import Checkpointer, pack_keys

    _, _, state, _ = _setup()
    with ocp.CheckpointManager(tmp_path / "ck") as mgr:
        mgr.save(2, args=ocp.args.StandardSave(pack_keys(state)), force=True)
        mgr.wait_until_finished()
    with Checkpointer(tmp_path / "ck") as ck:
        restored = ck.restore(state)
        assert ck.restore_metrics(2) == {}
    _assert_states_equal(state, restored)


def test_restore_missing_raises(tmp_path):
    _, _, state0, _ = _setup()
    with Checkpointer(tmp_path / "none") as ck:
        with pytest.raises(FileNotFoundError):
            ck.restore(state0)
