"""Device-resident data plane (ISSUE 13): ring bookkeeping (drop-oldest
+ staleness-bound semantics carried over from TrajQueue), codec
round-trips through the device ring, the host-numpy codec mirror vs the
device decode, checkpoint strip/resume-reattach of ring quant stats,
the off-policy device ingest, and the R2D2-style sequence consumer."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from actor_critic_tpu.data_plane import codecs as np_codecs
from actor_critic_tpu.data_plane import device_replay
from actor_critic_tpu.data_plane import ring as dp_ring
from actor_critic_tpu.replay import quantize


def _spec(shape=(3, 2), dtype=np.float32, **extra):
    out = {"x": jax.ShapeDtypeStruct(shape, dtype)}
    out.update(extra)
    return out


def _ring(depth=2, codec="fp32", spec=None, **kw):
    return dp_ring.DeviceTrajRing(
        depth=depth, block_spec=spec or _spec(), codec=codec,
        register_gauge=False, **kw,
    )


def _slot(ring, lease, name="x"):
    return np.asarray(
        ring.run(lambda state: state.storage[name][lease.slot])
    )


class TestRingBookkeeping:
    def test_init_shapes_and_codec_mix(self):
        spec = {
            "obs": jax.ShapeDtypeStruct((4, 2, 3), np.float32),
            "action": jax.ShapeDtypeStruct((4, 2), np.int64),
            "done": jax.ShapeDtypeStruct((4, 2), np.float32),
            "log_prob": jax.ShapeDtypeStruct((4, 2), np.float32),
        }
        ring = dp_ring.DeviceTrajRing(
            depth=3, block_spec=spec, codec="int8", register_gauge=False
        )
        st = ring._state
        assert st.storage["obs"].shape == (3, 4, 2, 3)
        assert st.storage["obs"].dtype == jnp.int8       # obs-family i8
        assert st.storage["done"].dtype == jnp.int8      # bool8
        assert st.storage["log_prob"].dtype == jnp.float32  # always raw
        assert st.storage["action"].dtype == jnp.int32   # raw, canonical
        assert st.versions.shape == (3,)
        assert "obs:i8" in ring.codec_mix()
        assert ring.bytes_per_block() < ring.raw_bytes_per_block()
        ring.close()

    def test_put_get_release_cycle(self):
        ring = _ring(depth=2)
        a = np.full((3, 2), 7.0, np.float32)
        assert ring.put({"x": a}, version=0, actor_id=1)
        lease = ring.get(timeout=1.0)
        assert (lease.version, lease.actor_id, lease.seq) == (0, 1, 0)
        np.testing.assert_array_equal(_slot(ring, lease), a)
        # The caller's array was copied at encode: mutate and re-check.
        a.fill(-1.0)
        np.testing.assert_array_equal(
            _slot(ring, lease), np.full((3, 2), 7.0, np.float32)
        )
        ring.release(lease)
        assert ring.get(timeout=0) is None
        ring.close()

    def test_device_version_tree_mirrors_host_bookkeeping(self):
        ring = _ring(depth=2)
        for v in range(2):
            ring.put({"x": np.full((3, 2), float(v), np.float32)}, version=v)
        st = ring._state
        assert sorted(np.asarray(st.versions).tolist()) == [0, 1]
        assert sorted(np.asarray(st.seqs).tolist()) == [0, 1]
        assert int(st.count) == 2
        ring.close()

    def test_drop_oldest_backpressure(self):
        ring = _ring(depth=2)
        for v in range(4):  # 2 slots, 4 puts: two oldest dropped
            assert ring.put(
                {"x": np.full((3, 2), float(v), np.float32)}, version=v
            )
        stats = ring.stats()
        assert stats["drops_full"] == 2
        lease = ring.get(timeout=1.0)
        assert lease.version == 2  # oldest SURVIVING block
        np.testing.assert_array_equal(
            _slot(ring, lease), np.full((3, 2), 2.0, np.float32)
        )
        ring.close()

    def test_drop_oldest_never_reclaims_leased_slot(self):
        ring = _ring(depth=1)
        assert ring.put({"x": np.zeros((3, 2), np.float32)}, version=0)
        lease = ring.get(timeout=1.0)
        # Single slot leased: a put must WAIT, not overwrite the lease.
        assert not ring.put(
            {"x": np.ones((3, 2), np.float32)}, version=1, timeout=0.05
        )
        np.testing.assert_array_equal(
            _slot(ring, lease), np.zeros((3, 2), np.float32)
        )
        ring.release(lease)
        assert ring.put({"x": np.ones((3, 2), np.float32)}, version=1)
        ring.close()

    def test_staleness_bound_drops_at_get(self):
        ring = _ring(depth=4, max_staleness=1)
        for v in range(3):
            ring.put({"x": np.full((3, 2), float(v), np.float32)}, version=v)
        ring.set_consumer_version(2)
        lease = ring.get(timeout=1.0)
        # versions 0 (lag 2) dropped; version 1 (lag 1) is consumable.
        assert lease.version == 1
        assert ring.stats()["drops_stale"] == 1
        ring.close()

    def test_block_policy_waits_for_free_slot(self):
        ring = _ring(depth=1, codec="fp32")
        ring.policy = "block"
        assert ring.put({"x": np.zeros((3, 2), np.float32)}, version=0)
        assert not ring.put(
            {"x": np.ones((3, 2), np.float32)}, version=1, timeout=0.05
        )
        lease = ring.get(timeout=1.0)
        ring.release(lease)
        assert ring.put({"x": np.ones((3, 2), np.float32)}, version=1)
        ring.close()

    def test_stats_gauge_row_fields(self):
        ring = _ring(depth=2, codec="fp32")
        ring.put({"x": np.zeros((3, 2), np.float32)}, version=0)
        s = ring.stats()
        assert s["consume_transfer_bytes"] == 0
        assert s["enqueue_bytes"] == 3 * 2 * 4
        assert s["bytes_per_block"] == s["raw_bytes_per_block"] == 24
        assert s["slots"] == s["capacity"] == 2
        ring.close()


class TestCodecsThroughRing:
    def test_fp32_roundtrip_is_bitwise(self):
        rng = np.random.default_rng(0)
        a = rng.normal(size=(3, 2)).astype(np.float32)
        ring = _ring(codec="fp32")
        ring.put({"x": a}, version=0)
        lease = ring.get(timeout=1.0)
        decoded = np.asarray(ring.run(
            lambda st: dp_ring.gather_block(st, lease.slot, ring.codecs)
        )["x"])
        np.testing.assert_array_equal(decoded, a)
        ring.close()

    @pytest.mark.parametrize("codec,bound", [("f16", 2e-3), ("int8", None)])
    def test_quantized_roundtrip_error_bounds(self, codec, bound):
        rng = np.random.default_rng(1)
        a = rng.normal(0, 2, size=(8, 4)).astype(np.float32)
        spec = {"obs": jax.ShapeDtypeStruct((8, 4), np.float32)}
        ring = dp_ring.DeviceTrajRing(
            depth=2, block_spec=spec, codec=codec, register_gauge=False
        )
        assert ring.codecs["obs"] == ("f16" if codec == "f16" else "i8")
        ring.put({"obs": a}, version=0)
        lease = ring.get(timeout=1.0)
        decoded = np.asarray(ring.run(
            lambda st: dp_ring.gather_block(st, lease.slot, ring.codecs)
        )["obs"])
        if bound is None:
            # i8: scale/127 per element, scale = running |x - mean| max.
            stats = ring.quant_host()["obs"]
            bound = float(stats["scale"]) / 127.0 + 1e-6
        assert np.max(np.abs(decoded - a)) <= bound
        ring.close()

    def test_int8_flags_and_small_ints_exact(self):
        spec = {
            "done": jax.ShapeDtypeStruct((4, 2), np.float32),
            "action": jax.ShapeDtypeStruct((4, 2), np.int64),
        }
        ring = dp_ring.DeviceTrajRing(
            depth=1, block_spec=spec, codec="int8", register_gauge=False
        )
        done = np.asarray([[0, 1]] * 4, np.float32)
        action = np.asarray([[0, 1]] * 4, np.int64)
        ring.put({"done": done, "action": action}, version=0)
        lease = ring.get(timeout=1.0)
        out = ring.run(
            lambda st: dp_ring.gather_block(st, lease.slot, ring.codecs)
        )
        np.testing.assert_array_equal(np.asarray(out["done"]), done)
        np.testing.assert_array_equal(np.asarray(out["action"]), action)
        ring.close()

    def test_host_mirror_matches_device_decode_exactly(self):
        """The i8 encode happens on the HOST (numpy) and the decode on
        device — with ONE stats tree both sides must reproduce the
        decode table exactly: decode(encode(x)) computed by numpy must
        equal the device's decode of the same codes bit-for-bit."""
        rng = np.random.default_rng(2)
        x = rng.normal(0, 3, size=(16, 4)).astype(np.float32)
        stats = np_codecs.np_init_stats("i8", ())
        stats = np_codecs.np_update_stats("i8", stats, x)
        codes = np_codecs.np_encode("i8", stats, x)
        host_decoded = np_codecs.np_decode("i8", stats, codes)
        dev_stats = quantize.QuantStats(
            mean=jnp.asarray(stats["mean"]),
            scale=jnp.asarray(stats["scale"]),
            count=jnp.asarray(stats["count"]),
        )
        dev_decoded = np.asarray(
            quantize.decode("i8", dev_stats, jnp.asarray(codes))
        )
        np.testing.assert_array_equal(host_decoded, dev_decoded)

    def test_np_stats_calibrate_then_freeze(self):
        stats = np_codecs.np_init_stats("i8", ())
        big = np.full((quantize.CALIBRATION_TRANSITIONS,), 5.0, np.float32)
        stats = np_codecs.np_update_stats("i8", stats, big)
        frozen_mean = float(stats["mean"])
        # Past calibration: a wildly different batch must not move them.
        stats2 = np_codecs.np_update_stats(
            "i8", stats, np.full((64,), -100.0, np.float32)
        )
        assert float(stats2["mean"]) == frozen_mean
        assert float(stats2["scale"]) == float(stats["scale"])

    def test_calibration_clock_counts_transitions_not_elements(self):
        """The freeze threshold is defined in TRANSITIONS: a
        [K, E, obs_dim] block must advance the clock by K*E, not
        K*E*obs_dim (which would freeze the window obs_dim× early,
        before the random-warmup coverage), and the [E, ...] last_obs
        by E."""
        stats = np_codecs.np_init_stats("i8", ())
        stats = np_codecs.np_update_stats(
            "i8", stats, np.ones((64, 8), np.float32), num_transitions=64
        )
        assert int(stats["count"]) == 64
        spec = {
            "obs": jax.ShapeDtypeStruct((4, 2, 3), np.float32),
            "reward": jax.ShapeDtypeStruct((4, 2), np.float32),
            "last_obs": jax.ShapeDtypeStruct((2, 3), np.float32),
        }
        ring = dp_ring.DeviceTrajRing(
            depth=2, block_spec=spec, codec="int8", register_gauge=False
        )
        assert ring._transitions_per_put == {
            "obs": 8, "reward": 8, "last_obs": 2,
        }
        rng = np.random.default_rng(0)
        ring.put({
            "obs": rng.normal(size=(4, 2, 3)).astype(np.float32),
            "reward": rng.normal(size=(4, 2)).astype(np.float32),
            "last_obs": rng.normal(size=(2, 3)).astype(np.float32),
        }, version=0)
        q = ring.quant_host()
        assert int(q["obs"]["count"]) == 8       # K*E, not K*E*obs_dim
        assert int(q["reward"]["count"]) == 8
        assert int(q["last_obs"]["count"]) == 2  # E rows
        ring.close()

    def test_raw_keys_never_quantize(self):
        spec = {
            "log_prob": jax.ShapeDtypeStruct((4, 2), np.float32),
            "value": jax.ShapeDtypeStruct((4, 2), np.float32),
            "action": jax.ShapeDtypeStruct((4, 2, 1), np.float32),
        }
        kinds = np_codecs.traj_codecs("int8", spec)
        assert kinds == {
            "log_prob": "raw", "value": "raw", "action": "raw"
        }

    def test_bad_codec_mode_rejected(self):
        with pytest.raises(ValueError, match="data-plane codec"):
            np_codecs.traj_codecs("bf16", _spec())


class TestCheckpointStats:
    def test_quant_host_install_roundtrip(self):
        rng = np.random.default_rng(3)
        spec = {"obs": jax.ShapeDtypeStruct((8, 4), np.float32)}
        ring = dp_ring.DeviceTrajRing(
            depth=2, block_spec=spec, codec="int8", register_gauge=False
        )
        ring.put({"obs": rng.normal(0, 2, (8, 4)).astype(np.float32)},
                 version=0)
        saved = ring.quant_host()
        assert float(saved["obs"]["scale"]) > quantize._EPS
        ring.close()
        # Fresh ring (resume-reattach): storage zeroed, stats restored —
        # new blocks encode against the run's original standardization.
        ring2 = dp_ring.DeviceTrajRing(
            depth=2, block_spec=spec, codec="int8", register_gauge=False
        )
        ring2.install_quant(saved)
        again = ring2.quant_host()
        for k in ("mean", "scale", "count"):
            np.testing.assert_array_equal(
                again["obs"][k], saved["obs"][k]
            )
        # And the DEVICE quant tree matches too (decode path).
        np.testing.assert_array_equal(
            np.asarray(ring2._state.quant["obs"].scale),
            saved["obs"]["scale"],
        )
        ring2.close()

    def test_async_ppo_device_plane_ckpt_strip_resume(self, tmp_path):
        """e2e: a device-plane async PPO run checkpoints (ring storage
        stripped by construction — only quant stats ride the tree),
        resumes, and REFUSES a data-plane flip."""
        gym = pytest.importorskip("gymnasium")  # noqa: F841
        from actor_critic_tpu.algos import ppo
        from actor_critic_tpu.envs.host_pool import HostEnvPool
        from actor_critic_tpu.utils.checkpoint import Checkpointer

        cfg = ppo.PPOConfig(
            num_envs=2, rollout_steps=4, epochs=1, num_minibatches=1,
            hidden=(8,),
        )
        ckpt_dir = tmp_path / "ck"

        def run(iters, resume, plane="device"):
            pool = HostEnvPool("CartPole-v1", 2, seed=0)
            ckpt = Checkpointer(str(ckpt_dir))
            try:
                return ppo.train_host_async(
                    [pool], cfg, iters, seed=0, log_every=1,
                    correction="vtrace", data_plane=plane,
                    plane_codec="int8", ckpt=ckpt, save_every=2,
                    resume=resume,
                )
            finally:
                ckpt.close()
                pool.close()

        run(2, resume=False)
        # Resume continues from block 2 with the restored quant stats.
        _, _, hist = run(4, resume=True)
        assert [it for it, _ in hist] == [3, 4]
        # A host-plane resume into a device-plane checkpoint must fail
        # with advice, not an orbax structure error.
        with pytest.raises(ValueError, match="data-plane"):
            run(6, resume=True, plane="host")


class TestOffPolicyDevicePlane:
    def test_ddpg_device_ingest_fills_replay(self):
        """The jitted gather+decode+ingest program: a staged block lands
        in the replay ring bit-consistently with the host-path ingest
        under the fp32 codec."""
        from actor_critic_tpu.algos import ddpg
        from actor_critic_tpu.algos.common import OffPolicyTransition
        from actor_critic_tpu.envs.jax_env import EnvSpec

        spec = EnvSpec(
            obs_shape=(3,), action_dim=1, discrete=False,
            obs_dtype=np.float32, can_truncate=True,
        )
        cfg = ddpg.DDPGConfig(
            num_envs=2, steps_per_iter=4, updates_per_iter=1,
            buffer_capacity=64, batch_size=4, warmup_steps=0, hidden=(8,),
        )
        rng = np.random.default_rng(0)
        K, E = cfg.steps_per_iter, cfg.num_envs
        block = {
            "obs": rng.normal(size=(K, E, 3)).astype(np.float32),
            "action": np.tanh(rng.normal(size=(K, E, 1))).astype(np.float32),
            "reward": rng.normal(size=(K, E)).astype(np.float32),
            "done": np.zeros((K, E), np.float32),
            "terminated": np.zeros((K, E), np.float32),
            "final_obs": rng.normal(size=(K, E, 3)).astype(np.float32),
            "last_obs": rng.normal(size=(E, 3)).astype(np.float32),
        }
        block_spec = device_replay.offpolicy_block_spec(spec, cfg, 1)
        ring = dp_ring.DeviceTrajRing(
            depth=2, block_spec=block_spec, codec="fp32",
            register_gauge=False,
        )
        ring.put(block, version=0)
        lease = ring.get(timeout=1.0)
        ingest = ddpg.make_device_ingest_update(
            spec.action_dim, cfg, ring.codecs
        )
        learner = ddpg.init_learner((3,), 1, cfg, jax.random.key(0))
        learner, _ = ring.run(
            lambda st: ingest(
                learner, st, np.int32(lease.slot), np.int32(0)
            )
        )
        ring.release(lease)
        ring.close()
        assert int(learner.replay.size) == K * E
        # The ring scattered exactly the block's transitions.
        host = OffPolicyTransition(
            obs=block["obs"], action=block["action"],
            reward=block["reward"], next_obs=block["final_obs"],
            terminated=block["terminated"], done=block["done"],
        )
        flat = jax.tree.map(
            lambda x: x.reshape(-1, *x.shape[2:]), host
        )
        np.testing.assert_array_equal(
            np.asarray(learner.replay.storage.obs[: K * E]), flat.obs
        )
        np.testing.assert_array_equal(
            np.asarray(learner.replay.storage.reward[: K * E]),
            flat.reward,
        )


class TestWarmupPlanners:
    def test_offpolicy_device_plan_and_aot_compile(self):
        """A ddpg device-plane context plans exactly the device ingest +
        ring enqueue (plus the mirror-independent fused-free set), and
        the thunks AOT-compile cleanly — every new jitted entry point
        has a working planner."""
        from actor_critic_tpu.algos import ddpg
        from actor_critic_tpu.envs.jax_env import EnvSpec
        from actor_critic_tpu.utils import compile_cache

        spec = EnvSpec(
            obs_shape=(3,), action_dim=1, discrete=False,
            obs_dtype=np.float32, can_truncate=True,
        )
        cfg = ddpg.DDPGConfig(
            num_envs=2, steps_per_iter=4, updates_per_iter=1,
            buffer_capacity=64, batch_size=4, warmup_steps=0, hidden=(8,),
        )
        ctx = compile_cache.WarmupContext(
            algo="ddpg", fused=False, spec=spec, cfg=cfg,
            eval_every=0, overlap=True, async_actors=2,
            data_plane="device", plane_codec="int8", queue_depth=3,
        )
        plan = dict(compile_cache.plan_warmup(ctx))
        assert "device_replay.make_device_ingest_update" in plan
        assert "ring.make_enqueue" in plan
        # The host-plane ingest planner must NOT also fire: a device
        # run never dispatches the argument-fed program, so warming it
        # would be a wasted compile.
        assert "ddpg.make_host_ingest_update" not in plan
        for name, thunk in plan.items():
            thunk()  # AOT lower+compile must succeed

    def test_host_plane_context_plans_no_device_programs(self):
        from actor_critic_tpu.algos import ddpg
        from actor_critic_tpu.envs.jax_env import EnvSpec
        from actor_critic_tpu.utils import compile_cache

        spec = EnvSpec(
            obs_shape=(3,), action_dim=1, discrete=False,
            obs_dtype=np.float32, can_truncate=True,
        )
        cfg = ddpg.DDPGConfig(num_envs=2, steps_per_iter=4, hidden=(8,))
        ctx = compile_cache.WarmupContext(
            algo="ddpg", fused=False, spec=spec, cfg=cfg,
            eval_every=0, overlap=True, async_actors=2,
        )
        names = [n for n, _ in compile_cache.plan_warmup(ctx)]
        assert "device_replay.make_device_ingest_update" not in names
        assert "ring.make_enqueue" not in names


class TestSequenceConsumer:
    def _seq(self, done_rows):
        """OffPolicyTransition-shaped [B, L] windows with given dones."""
        from actor_critic_tpu.algos.common import OffPolicyTransition

        done = jnp.asarray(done_rows, jnp.float32)
        B, L = done.shape
        base = jnp.arange(B * L, dtype=jnp.float32).reshape(B, L)
        return OffPolicyTransition(
            obs=base[..., None], action=base[..., None], reward=base,
            next_obs=base[..., None], terminated=done, done=done,
        )

    def test_window_mask_alive_before_done(self):
        mask = device_replay.sequence_window_mask(
            jnp.asarray([[0, 1, 0, 0], [0, 0, 0, 0]], jnp.float32)
        )
        # Done step itself valid (terminal reward counts); after, not.
        np.testing.assert_array_equal(
            np.asarray(mask), [[1, 1, 0, 0], [1, 1, 1, 1]]
        )

    def test_mask_matches_nstep_batch_convention(self):
        """The R2D2 mask and ddpg.nstep_batch must agree on which steps
        belong to the window's episode: the masked reward prefix sum at
        gamma=1 equals nstep_batch's return G."""
        from actor_critic_tpu.algos import ddpg

        seq = self._seq([[0, 1, 0], [0, 0, 0], [1, 0, 0]])
        batch, _ = ddpg.nstep_batch(seq, gamma=1.0)
        mask = device_replay.sequence_window_mask(seq.done)
        np.testing.assert_allclose(
            np.asarray(batch.reward),
            np.asarray(jnp.sum(seq.reward * mask, axis=1)),
        )

    def test_split_burn_in_shapes_and_cross_boundary_mask(self):
        seq = self._seq([[0, 1, 0, 0, 0]])  # done inside the burn-in
        burn, train, train_mask = device_replay.split_burn_in(seq, 2)
        assert burn.reward.shape == (1, 2)
        assert train.reward.shape == (1, 3)
        # The burn-in's done invalidates EVERY train step: they belong
        # to the next episode (the splice the mask exists to prevent).
        np.testing.assert_array_equal(np.asarray(train_mask), [[0, 0, 0]])

    def test_split_burn_in_zero_is_passthrough(self):
        seq = self._seq([[0, 0, 1]])
        burn, train, mask = device_replay.split_burn_in(seq, 0)
        assert burn is None
        np.testing.assert_array_equal(
            np.asarray(train.reward), np.asarray(seq.reward)
        )
        np.testing.assert_array_equal(np.asarray(mask), [[1, 1, 1]])

    def test_sample_training_sequences_draws_consecutive_inserts(self):
        from actor_critic_tpu import replay

        example = {"v": jnp.zeros((), jnp.float32),
                   "done": jnp.zeros((), jnp.float32)}
        state = replay.init(example, capacity=32)
        fill = {
            "v": jnp.arange(24, dtype=jnp.float32),
            "done": jnp.zeros(24, jnp.float32),
        }
        state = replay.add_batch(state, fill)
        out = replay.sample_sequences(
            state, jax.random.key(0), 16, 6
        )
        v = np.asarray(out["v"])
        # Every window is consecutive inserts (contract point 1).
        np.testing.assert_array_equal(np.diff(v, axis=1), 1.0)


def test_run_report_device_ring_row():
    """The run-report Resources section renders the device-ring gauge
    row (slots x bytes/block x codec mix; ISSUE 13 satellite)."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "run_report",
        Path(__file__).parent.parent / "scripts" / "run_report.py",
    )
    run_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(run_report)

    rows = [
        {"ts": 1.0, "recompiles": 0,
         "device_ring": {"capacity": 4, "slots": 4,
                         "bytes_per_block": 2960,
                         "raw_bytes_per_block": 7232,
                         "enqueue_bytes": 148000,
                         "consume_transfer_bytes": 0,
                         "codec_mix": "obs:i8,log_prob:raw",
                         "observe_staleness": 1, "staleness_max": 2,
                         "drops_full": 3, "drops_stale": 0,
                         "learner_idle_s": 0.42}},
    ]
    text = "\n".join(run_report.resource_summary(rows))
    assert "device ring" in text
    assert "4 slots x 2960 B/block" in text
    assert "raw 7232 B" in text
    assert "consume transfers 0 B" in text
    assert "3 full" in text
