"""Serve-while-training gateway fleet (ISSUE 17): the live learner
publish hook feeds a resident gateway, N replicas behind the fronting
proxy (relay, failover, health eviction/readmission, verbatim app-level
503), mailbox-driven replica policy sync, continuous-batching
refinements (overlapped dispatch, per-policy micro-batch windows, auto
backend), the open-loop load generator, and the serve_fleet.py CLI."""

from __future__ import annotations

import importlib.util
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import numpy as np
import pytest

from actor_critic_tpu import serving
from actor_critic_tpu.algos import ppo
from actor_critic_tpu.envs import make_cartpole
from actor_critic_tpu.envs.host_pool import HostEnvPool
from actor_critic_tpu.parallel import multihost

REPO = Path(__file__).parent.parent


# ---------------------------------------------------------------- helpers


class StubEngine:
    """jax-free engine: action = obs[:, 0] * params['scale'][0], with an
    optional dispatch pad and a max-concurrent-acts tracker (the overlap
    witness)."""

    max_rows = 8

    def __init__(self, pad_s: float = 0.0):
        self.pad_s = pad_s
        self._lock = threading.Lock()
        self._active = 0
        self.max_concurrent = 0

    def prepare_params(self, params):
        return {k: np.array(v) for k, v in params.items()}

    def act(self, params, obs):
        with self._lock:
            self._active += 1
            self.max_concurrent = max(self.max_concurrent, self._active)
        try:
            if self.pad_s:
                time.sleep(self.pad_s)
            obs = np.asarray(obs)
            return obs[:, 0] * params["scale"][0]
        finally:
            with self._lock:
                self._active -= 1


def _stub_store(scale: float = 2.0, pad_s: float = 0.0, **register_kw):
    store = serving.PolicyStore()
    engine = StubEngine(pad_s=pad_s)
    store.register(
        "default", engine,
        {"scale": np.full(1, scale, np.float32)}, **register_kw,
    )
    return store, engine


def _post(url: str, body: dict, timeout: float = 30.0):
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(url: str, timeout: float = 10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class _CannedReplica:
    """A stub upstream whose /v1/act answer is canned — the app-level
    503 relay tests need a replica that sheds/rejects on demand while
    its /healthz stays controllable."""

    def __init__(self, act_status: int = 200, act_body: dict | None = None):
        self.act_status = act_status
        self.act_body = act_body if act_body is not None else {"actions": [0.0]}
        self.healthy = True
        replica = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _send(self, status: int, payload: dict) -> None:
                raw = (json.dumps(payload) + "\n").encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def do_GET(self):
                if replica.healthy:
                    self._send(200, {"ok": True})
                else:
                    self._send(503, {"ok": False})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                if length:
                    self.rfile.read(length)
                self._send(replica.act_status, replica.act_body)

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._server.daemon_threads = True
        self.url = f"http://127.0.0.1:{self._server.server_address[1]}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


# ---------------------------------------------------------------- proxy


def test_proxy_round_robin_relays_and_counts():
    stores = [_stub_store(scale=2.0) for _ in range(2)]
    gws = [serving.ServeGateway(s, port=0, max_wait_us=0.0)
           for s, _ in stores]
    proxy = serving.FleetProxy(
        [gw.url for gw in gws], port=0, policy="round_robin", probe=False
    )
    try:
        for i in range(6):
            status, body = _post(
                proxy.url + "/v1/act", {"obs": [[float(i), 0.0]]}
            )
            assert status == 200, body
            assert body["actions"] == [pytest.approx(2.0 * i)]
        status, stats = _get(proxy.url + "/proxyz")
        assert status == 200
        assert stats["relayed"] == 6 and stats["failovers"] == 0
        assert stats["healthy"] == 2
        forwards = sorted(r["forwards"] for r in stats["replicas"])
        assert forwards == [3, 3]  # round robin splits evenly
    finally:
        proxy.close()
        for gw in gws:
            gw.close()


def test_proxy_failover_on_killed_replica():
    """Transport failure mid-fleet: the dead replica is evicted on
    first contact and every request still answers from the survivor —
    the gateway never surfaces the kill to the client."""
    stores = [_stub_store(scale=3.0) for _ in range(2)]
    gws = [serving.ServeGateway(s, port=0, max_wait_us=0.0)
           for s, _ in stores]
    proxy = serving.FleetProxy(
        [gw.url for gw in gws], port=0, policy="round_robin", probe=False
    )
    try:
        status, _ = _post(proxy.url + "/v1/act", {"obs": [[1.0, 0.0]]})
        assert status == 200
        gws[1].close()  # SIGKILL stand-in: connection refused from now on
        for i in range(8):
            status, body = _post(
                proxy.url + "/v1/act", {"obs": [[float(i), 0.0]]}
            )
            assert status == 200, body
            assert body["actions"] == [pytest.approx(3.0 * i)]
        status, stats = _get(proxy.url + "/proxyz")
        dead = next(
            r for r in stats["replicas"] if r["url"] == gws[1].url
        )
        assert not dead["healthy"] and dead["evictions"] >= 1
        assert stats["failovers"] >= 1
        assert stats["healthy"] == 1
    finally:
        proxy.close()
        gws[0].close()


def test_proxy_health_probe_evicts_and_readmits():
    """/healthz probing: `unhealthy_after` consecutive failures evict
    (a one-replica fleet then answers 503), one 200 readmits."""
    replica = _CannedReplica(act_status=200, act_body={"actions": [1.5]})
    proxy = serving.FleetProxy(
        [replica.url], port=0, unhealthy_after=2, probe=False
    )
    try:
        proxy.probe_once()
        assert proxy.stats()["healthy"] == 1
        replica.healthy = False
        proxy.probe_once()
        assert proxy.stats()["healthy"] == 1  # one failure: not yet
        proxy.probe_once()
        assert proxy.stats()["healthy"] == 0  # second consecutive: evicted
        status, body = _post(proxy.url + "/v1/act", {"obs": [[0.0]]})
        assert status == 503 and "no healthy replica" in body["error"]
        replica.healthy = True
        proxy.probe_once()  # a single 200 readmits immediately
        assert proxy.stats()["healthy"] == 1
        status, body = _post(proxy.url + "/v1/act", {"obs": [[0.0]]})
        assert status == 200 and body["actions"] == [1.5]
    finally:
        proxy.close()
        replica.close()


def test_proxy_relays_app_503_verbatim_without_failover():
    """A replica's admission-control shed is an APPLICATION answer: the
    proxy relays the 503 + shed body untouched and does NOT fail over —
    retrying a shed elsewhere would defeat the replica's admission
    control."""
    shedding = _CannedReplica(
        act_status=503, act_body={"error": "shedding", "shed": True}
    )
    proxy = serving.FleetProxy(
        [shedding.url], port=0, probe=False
    )
    try:
        for _ in range(3):
            status, body = _post(proxy.url + "/v1/act", {"obs": [[0.0]]})
            assert status == 503
            assert body.get("shed") is True and body["error"] == "shedding"
        stats = proxy.stats()
        assert stats["failovers"] == 0
        assert stats["healthy"] == 1  # app-level 503 never evicts
        assert stats["replicas"][0]["forwards"] == 3
    finally:
        proxy.close()
        shedding.close()


# ------------------------------------------------------- mailbox syncer


def test_mailbox_syncer_monotone_and_torn_tolerant(tmp_path):
    """poll_once consumes fresh versions, drops duplicates/stale
    regressions, and tolerates a torn snapshot file with the previous
    version still serving — the replica-side propagation contract
    fleetsan's replica schedules sweep."""
    mbox = str(tmp_path)
    store, _ = _stub_store(scale=0.0)
    template = {"scale": np.zeros(1, np.float32)}
    syncer = serving.MailboxPolicySyncer(
        store, "default", mbox, rank=0, template=template
    )
    assert syncer.poll_once() is False  # nothing published yet

    multihost.write_params(
        mbox, 0, 1, {"scale": np.full(1, 10.0, np.float32)}
    )
    assert syncer.poll_once() is True
    assert store.get("default").version == 1
    assert float(store.get("default").params["scale"][0]) == 10.0
    assert syncer.poll_once() is False  # duplicate delivery dropped

    multihost.write_params(
        mbox, 0, 3, {"scale": np.full(1, 30.0, np.float32)}
    )
    assert syncer.poll_once() is True and syncer.version == 3

    # Stale replay (an old snapshot re-landing in the mailbox) is
    # dropped by the per-publisher version clock.
    multihost.write_params(
        mbox, 0, 2, {"scale": np.full(1, 20.0, np.float32)}
    )
    assert syncer.poll_once() is False
    assert store.get("default").version == 3
    assert float(store.get("default").params["scale"][0]) == 30.0

    # Torn file: truncate the live snapshot mid-byte — read_params'
    # tolerance turns it into a no-op poll, never a torn swap.
    path = multihost.params_file(mbox, 0)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, size // 2))
    assert syncer.poll_once() is False
    assert store.get("default").version == 3

    multihost.write_params(
        mbox, 0, 4, {"scale": np.full(1, 40.0, np.float32)}
    )
    assert syncer.poll_once() is True
    assert store.get("default").version == 4 and syncer.swaps == 3


# ------------------------------------------------- serve-while-training


def test_serve_while_training_publishes_into_gateway():
    """The tentpole e2e: `publish_hook` rides the async learner's
    per-block publish into a resident gateway — served versions are
    strictly the swap sequence (monotone, ending at `iterations`), and
    the final served actions are bitwise the engine applied directly to
    the final published snapshot."""
    spec = make_cartpole().spec
    cfg = ppo.PPOConfig(
        num_envs=2, rollout_steps=4, epochs=1, num_minibatches=1,
        hidden=(8,),
    )
    engine = serving.PolicyEngine(spec, cfg, algo="ppo", buckets=(1, 2))
    store = serving.PolicyStore()
    template = serving.init_params(spec, cfg, "ppo", seed=0)
    store.register("learner", engine, template, default=True)
    engine.warm(store.get().params)
    gw = serving.ServeGateway(store, port=0, max_wait_us=0.0)

    obs = np.array([[0.02, -0.01, 0.03, 0.01]], np.float32)
    published: dict[int, object] = {}
    served_versions: list[int] = []

    def publish_hook(it: int, np_params) -> None:
        import jax

        published[it + 1] = jax.tree.map(np.array, np_params)
        store.swap("learner", np_params, version=it + 1)
        status, body = _post(gw.url + "/v1/act", {"obs": obs.tolist()})
        assert status == 200, body
        served_versions.append(body["version"])

    pool = HostEnvPool("CartPole-v1", num_envs=2, seed=0)
    try:
        ppo.train_host_async(
            [pool], cfg, 3, seed=0, log_every=0, queue_depth=1,
            publish_hook=publish_hook,
        )
        assert sorted(published) == [1, 2, 3]
        # Monotone: a later act never serves an older policy.
        assert served_versions == sorted(served_versions)
        assert store.get("learner").version == 3

        status, body = _post(gw.url + "/v1/act", {"obs": obs.tolist()})
        assert status == 200 and body["version"] == 3
        direct = engine.act(engine.prepare_params(published[3]), obs)
        np.testing.assert_array_equal(
            np.asarray(body["actions"]), np.asarray(direct)
        )
    finally:
        gw.close()
        pool.close()


# ------------------------------------------- continuous-batching knobs


def test_overlap_mode_correct_and_actually_concurrent():
    """max_inflight=2: flight workers dispatch concurrently (the stub
    engine witnesses >= 2 in-flight acts) and every request still gets
    exactly its own rows back."""
    store, engine = _stub_store(scale=2.0, pad_s=0.05)
    batcher = serving.MicroBatcher(
        store, max_wait_us=0.0, max_batch_rows=1, max_inflight=2
    )
    try:
        assert batcher.health()["max_inflight"] == 2
        reqs = [
            batcher.submit(np.full((1, 2), float(i), np.float32))
            for i in range(8)
        ]
        for i, req in enumerate(reqs):
            actions, _version = batcher.wait(req, timeout=30.0)
            assert actions == [pytest.approx(2.0 * i)]
        assert engine.max_concurrent >= 2, (
            "flight workers never overlapped a dispatch"
        )
    finally:
        batcher.close()


def test_per_policy_max_wait_overrides_global_window():
    """An SLO-classed policy's `max_wait_us` beats the batcher's global
    window: a zero-wait policy flushes immediately even when the global
    window would hold the flush far longer."""
    store = serving.PolicyStore()
    engine = StubEngine()
    store.register(
        "fast", engine, {"scale": np.ones(1, np.float32)},
        max_wait_us=0.0,
    )
    store.register("slow", engine, {"scale": np.ones(1, np.float32)})
    batcher = serving.MicroBatcher(store, max_wait_us=400_000.0)
    try:
        t0 = time.monotonic()
        req = batcher.submit(np.ones((1, 2), np.float32), "fast")
        batcher.wait(req, timeout=5.0)
        assert time.monotonic() - t0 < 0.25  # no 0.4 s global hold
        t0 = time.monotonic()
        req = batcher.submit(np.ones((1, 2), np.float32), "slow")
        batcher.wait(req, timeout=5.0)
        # The un-overridden policy still pays the global window (the
        # single 1-row request can never fill the 8-row budget).
        assert time.monotonic() - t0 >= 0.3
    finally:
        batcher.close()


def test_auto_backend_resolves_from_measured_walls():
    spec = make_cartpole().spec
    cfg = ppo.PPOConfig(hidden=(8,))
    engine = serving.PolicyEngine(
        spec, cfg, algo="ppo", buckets=(1, 2), backend="auto"
    )
    params = serving.init_params(spec, cfg, "ppo", seed=0)
    with pytest.raises(RuntimeError, match="unresolved"):
        engine.prepare_params(params)
    chosen = engine.resolve_backend(params, trials=3)
    assert chosen in ("xla", "mirror")
    assert engine.backend == chosen
    assert engine.auto_choice["backend"] == chosen
    assert engine.auto_choice["xla_ms"] > 0.0
    assert engine.auto_choice["mirror_ms"] > 0.0
    assert engine.resolve_backend(params) == chosen  # idempotent

    # The resolved engine serves exactly what a concretely-constructed
    # engine of the chosen backend serves.
    ref = serving.PolicyEngine(
        spec, cfg, algo="ppo", buckets=(1, 2), backend=chosen
    )
    obs = np.array(
        [[0.02, -0.01, 0.03, 0.01], [0.1, 0.0, -0.05, 0.2]], np.float32
    )
    np.testing.assert_array_equal(
        engine.act(engine.prepare_params(params), obs),
        ref.act(ref.prepare_params(params), obs),
    )


def test_auto_backend_with_sampling_fixes_xla():
    """The mirror serves greedy only, so a sampling engine has nothing
    to measure: backend='auto' degrades straight to the XLA path."""
    spec = make_cartpole().spec
    cfg = ppo.PPOConfig(hidden=(8,))
    engine = serving.PolicyEngine(
        spec, cfg, algo="ppo", backend="auto", sample=True
    )
    assert engine.backend == "xla"


# ------------------------------------------------------------- loadgen


def _load_loadgen():
    spec = importlib.util.spec_from_file_location(
        "serve_loadgen", REPO / "scripts" / "serve_loadgen.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_open_loop_loadgen_paces_to_fixed_rate():
    """`--rate R` offers R req/s on a fixed arrival schedule: the
    request count tracks rate x duration (not the service rate), and
    the open-loop accounting fields ride the summary."""
    loadgen = _load_loadgen()
    store, _ = _stub_store(scale=1.0)
    gw = serving.ServeGateway(store, port=0, max_wait_us=0.0)
    try:
        out = loadgen.run_load(
            gw.url, concurrency=4, duration_s=1.2, obs_dim=2,
            rate=50.0,
        )
        assert out["mode"] == "open"
        assert out["offered_per_s"] == 50.0
        assert out["errors"] == 0
        # The schedule admits ~rate*duration arrivals; a closed loop on
        # this near-zero-latency stub would fire thousands.
        assert 40 <= out["requests"] <= 65, out
        for key in ("late", "shed", "rejected_503"):
            assert key in out
        with pytest.raises(ValueError, match="rate"):
            loadgen.run_load(gw.url, duration_s=0.1, rate=-1.0)
    finally:
        gw.close()


def test_loadgen_discriminates_shed_from_plain_503():
    """The worker splits 503s by their body's `shed` marker — the
    admission-control shed and the queue-full reject stay separate all
    the way into the load report."""
    loadgen = _load_loadgen()
    shedding = _CannedReplica(
        act_status=503, act_body={"error": "shedding", "shed": True}
    )
    rejecting = _CannedReplica(
        act_status=503, act_body={"error": "queue full"}
    )
    try:
        out = loadgen.run_load(
            shedding.url, concurrency=2, duration_s=0.4, obs_dim=2
        )
        assert out["shed"] > 0 and out["rejected_503"] == 0
        assert out["errors"] == out["shed"]
        out = loadgen.run_load(
            rejecting.url, concurrency=2, duration_s=0.4, obs_dim=2
        )
        assert out["rejected_503"] > 0 and out["shed"] == 0
    finally:
        shedding.close()
        rejecting.close()


# ----------------------------------------------------------------- CLI


def test_serve_fleet_cli_relays_and_shuts_down():
    store, _ = _stub_store(scale=4.0)
    gw = serving.ServeGateway(store, port=0, max_wait_us=0.0)
    proc = subprocess.Popen(
        [
            sys.executable, str(REPO / "scripts" / "serve_fleet.py"),
            "--replica", gw.url, "--port", "0",
            "--health-interval", "0.2",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        line = proc.stdout.readline()
        m = re.search(r"fleet proxy on (http://[\d.]+:\d+)", line)
        assert m, f"no proxy URL in startup line: {line!r}"
        url = m.group(1)
        status, body = _post(url + "/v1/act", {"obs": [[2.0, 0.0]]})
        assert status == 200 and body["actions"] == [pytest.approx(8.0)]
        status, stats = _get(url + "/proxyz")
        assert status == 200 and stats["relayed"] >= 1
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=15)
        assert proc.returncode == 0
        assert "fleet proxy closed" in out
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=10)
        gw.close()
