"""scripts/summarize_run.py — the JSONL→BASELINE-row summarizer.

The tricky part is resume stitching: `wall_s` is per-process, so a
resumed run (scripts/run_resumable.sh) resets it, and the summarizer
must (a) sum segment maxima into the total, (b) detect a restart even
when the new process's first logged wall_s already exceeds the previous
segment's last (the iter field going non-increasing is the signal), and
(c) report eval positions in resume-summed wall-clock.
"""

import importlib.util
import json
import sys
from pathlib import Path

spec = importlib.util.spec_from_file_location(
    "summarize_run", Path(__file__).parent.parent / "scripts" / "summarize_run.py"
)
summarize_run = importlib.util.module_from_spec(spec)
spec.loader.exec_module(summarize_run)


def _write(tmp_path, rows):
    p = tmp_path / "m.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    return str(p)


def test_single_segment(tmp_path):
    rows = [
        {"iter": 10, "wall_s": 5.0, "env_steps": 100.0},
        {"iter": 20, "wall_s": 9.0, "env_steps": 200.0, "eval_return": 3.0},
        {"iter": 30, "wall_s": 14.0, "env_steps": 300.0},
    ]
    s = summarize_run.summarize(_write(tmp_path, rows))
    assert s["segments"] == 1
    assert s["wall_s_sum"] == 14.0
    assert s["env_steps"] == 300.0
    assert s["best_eval"] == 3.0 and s["best_eval_at_wall_s"] == 9.0


def test_resume_detected_by_wall_decrease(tmp_path):
    rows = [
        {"iter": 10, "wall_s": 100.0, "env_steps": 100.0},
        # resume from ckpt at iter 10; wall restarts lower
        {"iter": 20, "wall_s": 7.0, "env_steps": 200.0, "eval_return": 5.0},
        {"iter": 30, "wall_s": 12.0, "env_steps": 300.0},
    ]
    s = summarize_run.summarize(_write(tmp_path, rows))
    assert s["segments"] == 2
    assert s["wall_s_sum"] == 112.0  # 100 + 12
    # Best eval landed 7s into segment 2 → 107s of summed wall-clock.
    assert s["best_eval_at_wall_s"] == 107.0


def test_resume_detected_by_iter_regression(tmp_path):
    # Segment 1 dies at wall_s=5; segment 2's first log (after a slow
    # restore/compile) is already at wall_s=8 — wall_s never decreases,
    # but iter regresses to the checkpointed 10.
    rows = [
        {"iter": 10, "wall_s": 5.0, "env_steps": 100.0},
        {"iter": 10, "wall_s": 8.0, "env_steps": 100.0},
        {"iter": 20, "wall_s": 16.0, "env_steps": 200.0},
    ]
    s = summarize_run.summarize(_write(tmp_path, rows))
    assert s["segments"] == 2
    assert s["wall_s_sum"] == 21.0  # 5 + 16
    assert s["steps_per_sec"] == round(200.0 / 21.0, 1)


def test_empty_file(tmp_path):
    s = summarize_run.summarize(_write(tmp_path, []))
    assert s.get("empty") is True


def test_null_eval_rows_skipped(tmp_path):
    # JsonlLogger scrubs NaN to null; a diverged run's eval rows must
    # not crash the summary (and must not count as evals).
    rows = [
        {"iter": 10, "wall_s": 5.0, "env_steps": 100.0, "eval_return": None},
        {"iter": 20, "wall_s": 9.0, "env_steps": 200.0, "eval_return": 4.0},
    ]
    s = summarize_run.summarize(_write(tmp_path, rows))
    assert s["eval_count"] == 1 and s["best_eval"] == 4.0


def test_torn_final_line_tolerated(tmp_path):
    p = tmp_path / "m.jsonl"
    p.write_text(
        json.dumps({"iter": 10, "wall_s": 5.0, "env_steps": 100.0}) + "\n"
        + '{"iter": 20, "wall_s'  # process killed mid-write
    )
    s = summarize_run.summarize(str(p))
    assert s["rows"] == 1 and s["bad_lines"] == 1
    assert s["final_iter"] == 10
