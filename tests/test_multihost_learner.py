"""Multi-host distributed learner (ISSUE 9, parallel/multihost.py).

Fast units run un-marked: the param mailbox's latest-wins frozen-
snapshot contract, the gossip ring schedule's full-fleet coverage, the
mixing step, the filesystem mailbox transport (atomic publish + torn-
read tolerance), the FileMailboxWriter thread (the `mailbox` role the
thread model learns), and the launcher's fleet-trace merge.

The multi-process cluster exercises are `slow` (each spawns fresh
interpreters against a localhost coordinator); tier-1 covers the
2-process sync path through `scripts/tier1.sh`'s own smoke step
(`launch_multihost.py --smoke`, under its own timeout), and the
`multihost_scaling` bench record carries the 1/2/4-process evidence.
"""

import importlib.util
import json
import os
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from actor_critic_tpu.parallel import multihost

REPO = Path(__file__).parent.parent


def _load_launcher():
    spec = importlib.util.spec_from_file_location(
        "launch_multihost", REPO / "scripts" / "launch_multihost.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------- ParamMailbox

def test_mailbox_latest_wins_and_take_once():
    mb = multihost.ParamMailbox()
    assert mb.take() is None and mb.peek() is None
    mb.deposit({"w": np.ones(2, np.float32)}, version=1, peer=2)
    mb.deposit({"w": np.full(2, 2.0, np.float32)}, version=3, peer=1)
    version, peer, params = mb.take()
    assert (version, peer) == (3, 1)
    assert float(params["w"][0]) == 2.0
    assert mb.take() is None          # consumed; nothing newer yet
    assert mb.peek()[0] == 3          # peek never consumes
    # Same-peer regression is dropped — the learner must never mix one
    # peer backwards.
    assert not mb.deposit({"w": np.zeros(2, np.float32)}, version=1, peer=1)
    assert mb.take() is None
    # But versions are PER-PEER clocks: a lower-numbered snapshot from a
    # DIFFERENT peer (the ring rotated onto a slower host) still lands —
    # a slow peer must keep diffusing, not be muted by the fastest
    # version ever seen.
    assert mb.deposit({"w": np.full(2, 5.0, np.float32)}, version=2, peer=0)
    version, peer, params = mb.take()
    assert (version, peer) == (2, 0)
    assert float(params["w"][0]) == 5.0
    assert mb.stats()["deposits"] == 3


def test_mailbox_frozen_snapshot_contract():
    """Same contract as PolicyPublisher.publish (ISSUE 7): the stored
    tree is a read-only COPY — the depositor keeps no writable alias of
    what the learner consumes, and consumer-side mutation crashes."""
    mb = multihost.ParamMailbox()
    tree = {"w": np.ones(2, np.float32)}
    mb.deposit(tree, version=1, peer=0)
    tree["w"][0] = 9.0                # depositor's own tree: writable
    _, _, stored = mb.take()
    assert float(stored["w"][0]) == 1.0  # snapshot taken before the 9.0
    with pytest.raises(ValueError, match="read-only"):
        stored["w"][0] = 3.0


# ------------------------------------------------------- gossip ring + mix

def test_gossip_peer_rotates_through_whole_fleet():
    for world in (2, 3, 4, 8):
        for rank in range(world):
            peers = {
                multihost.gossip_peer(rank, world, r)
                for r in range(world - 1)
            }
            assert peers == set(range(world)) - {rank}, (rank, world)


def test_gossip_peer_rejects_singleton_fleet():
    with pytest.raises(ValueError, match="at least 2"):
        multihost.gossip_peer(0, 1, 0)


def test_mix_params_convex_and_dtype_preserving():
    own = {"w": np.full((2,), 2.0, np.float32), "b": np.zeros((1,), np.float32)}
    peer = {"w": np.full((2,), 4.0, np.float32), "b": np.ones((1,), np.float32)}
    mixed = multihost.mix_params(own, peer, 0.25)
    np.testing.assert_allclose(mixed["w"], 2.5)
    np.testing.assert_allclose(mixed["b"], 0.25)
    assert mixed["w"].dtype == np.float32
    # weight 0 = own, weight 1 = peer
    np.testing.assert_allclose(multihost.mix_params(own, peer, 0.0)["w"], 2.0)
    np.testing.assert_allclose(multihost.mix_params(own, peer, 1.0)["w"], 4.0)


# ----------------------------------------------------- filesystem transport

def test_write_read_params_roundtrip(tmp_path):
    params = {
        "a": np.arange(6, dtype=np.float32).reshape(2, 3),
        "nested": {"b": np.full((4,), 7.0, np.float32)},
    }
    multihost.write_params(str(tmp_path), 3, 11, params)
    out = multihost.read_params(str(tmp_path), 3, params)
    assert out is not None
    version, tree = out
    assert version == 11
    np.testing.assert_array_equal(tree["a"], params["a"])
    np.testing.assert_array_equal(tree["nested"]["b"], params["nested"]["b"])
    # Unpublished peer: None, not an exception.
    assert multihost.read_params(str(tmp_path), 9, params) is None
    # Overwrite is latest-wins (one file per host).
    multihost.write_params(str(tmp_path), 3, 12, params)
    assert multihost.read_params(str(tmp_path), 3, params)[0] == 12
    # No .tmp litter after the atomic replace.
    host_dir = tmp_path / "host3"
    assert [p.name for p in host_dir.iterdir()] == ["params.npz"]


def test_read_params_tolerates_garbage_file(tmp_path):
    path = Path(multihost.params_file(str(tmp_path), 0))
    path.parent.mkdir(parents=True)
    path.write_bytes(b"definitely not an npz")
    assert multihost.read_params(str(tmp_path), 0, {"w": np.ones(1)}) is None


def test_file_mailbox_writer_deposits_scheduled_peer(tmp_path):
    """The mailbox-writer thread (role `mailbox` in the thread model)
    polls the ring-scheduled peer's snapshot into the in-memory
    mailbox; fresh versions land, the learner's `set_round` redirects
    it."""
    template = {"w": np.zeros((2,), np.float32)}
    # world=3, rank=0: round 0 reads peer 1, round 1 reads peer 2.
    multihost.write_params(str(tmp_path), 1, 5, {"w": np.full((2,), 1.0, np.float32)})
    multihost.write_params(str(tmp_path), 2, 9, {"w": np.full((2,), 2.0, np.float32)})
    mailbox = multihost.ParamMailbox()
    stop = threading.Event()
    writer = multihost.FileMailboxWriter(
        str(tmp_path), 0, 3, template=template, mailbox=mailbox,
        stop=stop, poll_s=0.01,
    ).start()
    try:
        deadline = time.monotonic() + 5.0
        out = None
        while out is None and time.monotonic() < deadline:
            out = mailbox.take()
            time.sleep(0.01)
        assert out is not None, "writer never deposited"
        version, peer, params = out
        assert (version, peer) == (5, 1)
        assert float(params["w"][0]) == 1.0
        writer.set_round(1)  # ring advances to peer 2
        out = None
        while out is None and time.monotonic() < deadline:
            out = mailbox.take()
            time.sleep(0.01)
        assert out is not None
        assert (out[0], out[1]) == (9, 2)
    finally:
        stop.set()
        writer.join(timeout=5.0)
    assert writer.error is None


def test_thread_model_learns_mailbox_writer_role():
    """ISSUE 9 satellite: the concurrency passes' whole-repo thread
    model must resolve the FileMailboxWriter spawn to the `mailbox`
    role (its shared round counter carries the audited thread-owned
    annotation the passes rely on)."""
    from actor_critic_tpu.analysis.core import load_modules
    from actor_critic_tpu.analysis.thread_model import ThreadModel

    path = str(REPO / "actor_critic_tpu" / "parallel" / "multihost.py")
    model = ThreadModel(load_modules([path], str(REPO)))
    spawns = [
        s for s in model.spawns
        if s.target_class == "FileMailboxWriter"
    ]
    assert spawns and spawns[0].role == "mailbox", model.spawns
    cls = model.classes[
        ("actor_critic_tpu/parallel/multihost.py", "FileMailboxWriter")
    ]
    assert "_run" in cls.thread_methods["mailbox"]
    assert cls.owned_attrs.get("_round") == "caller"


# -------------------------------------------------------- launcher helpers

def test_merge_host_traces_aligns_clocks(tmp_path):
    launcher = _load_launcher()
    for rank, epoch0 in ((0, 100.0), (1, 102.5)):
        host_dir = tmp_path / f"host{rank}"
        host_dir.mkdir()
        events = [
            {"name": "process_name", "ph": "M", "pid": 1000 + rank,
             "tid": 0, "args": {"name": f"host{rank}"}},
            {"name": "clock_sync", "ph": "M", "pid": 1000 + rank,
             "tid": 0, "args": {"unix_epoch_at_ts0": epoch0}},
            {"name": "iteration", "ph": "X", "ts": 10.0, "dur": 5.0,
             "pid": 1000 + rank, "tid": 1, "cat": "phase"},
        ]
        (host_dir / "spans.jsonl").write_text(
            "\n".join(json.dumps(e) for e in events) + "\n"
        )
    out = launcher.merge_host_traces(str(tmp_path), 2)
    assert out and os.path.exists(out)
    merged = [json.loads(ln) for ln in open(out)]
    spans = {e["pid"]: e for e in merged if e.get("ph") == "X"}
    # host0 anchors the axis; host1's events shift by the epoch delta.
    assert spans[1000]["ts"] == 10.0
    assert spans[1001]["ts"] == pytest.approx(10.0 + 2.5e6)
    # Per-host process_name lanes survive the merge.
    names = {
        e["pid"]: e["args"]["name"]
        for e in merged if e.get("name") == "process_name"
    }
    assert names == {1000: "host0", 1001: "host1"}


def test_block_spec_shards_env_axis():
    from jax.sharding import PartitionSpec as P

    from actor_critic_tpu.parallel.mesh import DP_AXIS

    assert multihost._block_spec(2) == P(None, DP_AXIS)
    assert multihost._block_spec(3) == P(None, DP_AXIS, None)


# ------------------------------------------------- multi-process clusters

@pytest.mark.slow
def test_two_process_sync_cluster_bit_consistent():
    """The acceptance row's 2-process leg: a localhost jax.distributed
    cluster trains real blocks through the global-mesh update and every
    iteration's all-reduced version counter and params fingerprint
    match `world x local` bit-exactly."""
    launcher = _load_launcher()
    rec = launcher.run_cluster(
        2, "sync", iterations=4, rollout_steps=8, num_envs=2, actors=1,
        sleep_s=0.0, timeout_s=300.0,
    )
    assert rec["version_consistent"], rec
    assert rec["fingerprint_consistent"], rec
    assert rec["consumed_env_steps"] == 2 * 4 * 8 * 2


@pytest.mark.slow
def test_two_process_gossip_cluster_mixes_without_barrier():
    launcher = _load_launcher()
    rec = launcher.run_cluster(
        2, "gossip", iterations=8, rollout_steps=8, num_envs=2,
        actors=1, sleep_s=0.0, timeout_s=300.0,
    )
    assert rec["gossip_mixes"] > 0, rec
    assert rec["consumed_env_steps"] == 2 * 8 * 8 * 2
