"""Distributed tests on the fake 8-device CPU mesh (SURVEY.md §4):
psum-grad equivalence with single-device, replication invariants, and a
dp learning smoke test."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.algos import a2c
from actor_critic_tpu.algos.common import Transition
from actor_critic_tpu.envs import make_two_state_mdp
from actor_critic_tpu.parallel import (
    DP_AXIS,
    distribute_state,
    make_dp_train_step,
    make_mesh,
    train_state_specs,
)

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (fake) devices"
)


def _mesh():
    return make_mesh()


def test_mesh_shape():
    mesh = _mesh()
    assert mesh.shape[DP_AXIS] == 8


def test_sharded_grad_equals_full_batch_grad():
    """pmean of per-shard grads == grad on the full batch (the core
    MirroredStrategy/NCCL-equivalence property, SURVEY §2.4)."""
    from jax.sharding import PartitionSpec as P

    from actor_critic_tpu.parallel.mesh import shard_map

    env = make_two_state_mdp()
    cfg = a2c.A2CConfig(num_envs=8, rollout_steps=4, hidden=(16,))
    net = a2c.make_network(env, cfg)
    params = net.init(jax.random.key(0), jnp.zeros((1, 2)))

    T, E = 4, 16
    rng = np.random.RandomState(0)
    traj = Transition(
        obs=jnp.asarray(rng.rand(T, E, 2), jnp.float32),
        action=jnp.asarray(rng.randint(0, 2, (T, E))),
        log_prob=jnp.zeros((T, E)),
        value=jnp.zeros((T, E)),
        reward=jnp.asarray(rng.rand(T, E), jnp.float32),
        done=jnp.zeros((T, E)),
        terminated=jnp.zeros((T, E)),
        final_obs=jnp.asarray(rng.rand(T, E, 2), jnp.float32),
    )
    adv = jnp.asarray(rng.randn(T, E), jnp.float32)
    ret = jnp.asarray(rng.randn(T, E), jnp.float32)

    def loss_grads(params, traj, adv, ret, axis_name=None):
        g = jax.grad(
            lambda p: a2c.a2c_loss(p, net.apply, traj, adv, ret, cfg)[0]
        )(params)
        if axis_name is not None:
            g = jax.tree.map(lambda x: jax.lax.pmean(x, axis_name), g)
        return g

    g_full = loss_grads(params, traj, adv, ret)

    mesh = _mesh()
    sharded = shard_map(
        lambda p, t, a, r: loss_grads(p, t, a, r, DP_AXIS),
        mesh=mesh,
        in_specs=(P(), P(None, DP_AXIS), P(None, DP_AXIS), P(None, DP_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    g_dp = sharded(params, traj, adv, ret)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        g_full,
        g_dp,
    )


def test_dp_train_step_runs_and_replicates():
    env = make_two_state_mdp()
    cfg = a2c.A2CConfig(num_envs=32, rollout_steps=4, hidden=(16,))
    mesh = _mesh()
    state = a2c.init_state(env, cfg, jax.random.key(0))
    state = distribute_state(state, mesh)
    step = make_dp_train_step(a2c.make_train_step(env, cfg, axis_name=DP_AXIS), mesh)

    state, metrics = step(state)
    jax.block_until_ready(state)  # see note in test_dp_learning_two_state
    state, metrics = step(state)
    jax.block_until_ready(state)

    # params must be bitwise identical across devices (replicated after pmean)
    leaf = jax.tree.leaves(state.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.update_step) == 2


def test_dp_learning_two_state():
    """8-device dp training still reaches the known optimum."""
    env = make_two_state_mdp()
    cfg = a2c.A2CConfig(
        num_envs=32, rollout_steps=8, lr=3e-3, gamma=0.9, hidden=(32,),
        entropy_coef=0.001,
    )
    mesh = _mesh()
    state = a2c.init_state(env, cfg, jax.random.key(1))
    state = distribute_state(state, mesh)
    step = make_dp_train_step(a2c.make_train_step(env, cfg, axis_name=DP_AXIS), mesh)
    for _ in range(200):
        state, metrics = step(state)
        # XLA CPU's InProcessCommunicator deadlocks (AwaitAndLogIfStuck →
        # SIGABRT) when in-flight executions of collective programs overlap
        # and >1 collective executable exists in the process — verified
        # in-session on the fake 8-device mesh. Serialize steps in tests;
        # real TPU execution does not have this constraint.
        jax.block_until_ready(state)
    net = a2c.make_network(env, cfg)
    dist, v = net.apply(state.params, jnp.eye(2))
    p1 = jax.nn.softmax(dist.logits)[:, 1]
    assert float(p1.min()) > 0.9, f"dp training failed to learn: P(a=1)={p1}"


def test_distribute_state_rejects_indivisible():
    env = make_two_state_mdp()
    cfg = a2c.A2CConfig(num_envs=12, rollout_steps=4, hidden=(16,))
    state = a2c.init_state(env, cfg, jax.random.key(0))
    with pytest.raises(ValueError, match="not divisible"):
        distribute_state(state, _mesh())


def test_dp_offpolicy_train_step_runs_shards_replay():
    """DDPG/TD3 fused trainer under dp: replay sharded over devices,
    params/targets replicated after pmean, per-device sampling streams
    (BASELINE.json:5 'replay buffer lives in HBM as a sharded
    DeviceArray')."""
    from jax.sharding import PartitionSpec as P

    from actor_critic_tpu.algos import ddpg
    from actor_critic_tpu.envs import make_point_mass
    from actor_critic_tpu.parallel import offpolicy_state_specs

    env = make_point_mass()
    cfg = ddpg.td3_config(
        num_envs=16, steps_per_iter=4, updates_per_iter=2,
        buffer_capacity=512, batch_size=8, warmup_steps=0, hidden=(16,),
    )
    mesh = _mesh()
    state = ddpg.init_state(env, cfg, jax.random.key(0))
    state = distribute_state(state, mesh, offpolicy_state_specs())

    # The ring's storage really is dp-sharded: each device owns 512/8 rows.
    obs_leaf = state.learner.replay.storage.obs
    assert obs_leaf.sharding.spec == P(DP_AXIS)
    assert obs_leaf.addressable_shards[0].data.shape[0] == 512 // 8

    step = make_dp_train_step(
        ddpg.make_train_step(env, cfg, axis_name=DP_AXIS),
        mesh,
        offpolicy_state_specs(),
    )
    state, metrics = step(state)
    jax.block_until_ready(state)  # see note in test_dp_learning_two_state
    state, metrics = step(state)
    jax.block_until_ready(state)

    # Params and targets bitwise identical across devices (pmean-ed grads).
    for tree in (
        state.learner.actor_params, state.learner.critic_params,
        state.learner.target_actor, state.learner.target_critic,
    ):
        leaf = jax.tree.leaves(tree)[0]
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)
    # Each device's sub-ring received ITS OWN env shard's transitions
    # (different envs → different obs), so replay shards must differ.
    # (Re-read from the post-step state: the step donates its input, so
    # the pre-step obs_leaf buffer no longer exists.)
    shard0, shard1 = (
        np.asarray(s.data)
        for s in state.learner.replay.storage.obs.addressable_shards[:2]
    )
    assert not np.array_equal(shard0, shard1)
    # Cursor scalars evolved identically (replicated): 2 iters × 4 steps
    # × 2 local envs = 16 local inserts.
    assert int(state.learner.replay.size) == 16
    assert np.isfinite(float(metrics["critic_loss"]))
    assert int(state.learner.update_count) == 4


def test_dp_offpolicy_quantized_replay_shards_and_syncs_stats():
    """ISSUE 8: the QUANTIZED ring under dp on the 8-device CPU mesh —
    int8 storage sharded over devices like the fp32 ring, quantizer
    running stats replicated AND bit-identical across devices (add_batch
    pmean/pmax-syncs the batch moments over the dp axis), train step
    runs with finite losses."""
    from jax.sharding import PartitionSpec as P

    from actor_critic_tpu.algos import ddpg
    from actor_critic_tpu.envs import make_point_mass
    from actor_critic_tpu.parallel import offpolicy_state_specs

    env = make_point_mass()
    cfg = ddpg.td3_config(
        num_envs=16, steps_per_iter=4, updates_per_iter=2,
        buffer_capacity=512, batch_size=8, warmup_steps=0, hidden=(16,),
        replay_dtype="mixed",
    )
    mesh = _mesh()
    state = ddpg.init_state(env, cfg, jax.random.key(0))
    state = distribute_state(state, mesh, offpolicy_state_specs())

    obs_leaf = state.learner.replay.storage.obs
    assert obs_leaf.dtype == jnp.int8  # quantized storage, dp-sharded
    assert obs_leaf.sharding.spec == P(DP_AXIS)
    assert obs_leaf.addressable_shards[0].data.shape[0] == 512 // 8

    step = make_dp_train_step(
        ddpg.make_train_step(env, cfg, axis_name=DP_AXIS),
        mesh,
        offpolicy_state_specs(),
    )
    state, metrics = step(state)
    jax.block_until_ready(state)
    state, metrics = step(state)
    jax.block_until_ready(state)

    # Quantizer stats: live (count > 0, scale grew) and IDENTICAL on
    # every device — each device folds different env transitions, so
    # only the cross-device moment sync keeps the replicated spec true.
    stats = state.learner.replay.quant.obs
    assert int(stats.count) > 0
    for leaf in (stats.mean, stats.scale):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)
    # The sub-rings themselves still differ (per-device env shards).
    shard0, shard1 = (
        np.asarray(s.data)
        for s in state.learner.replay.storage.obs.addressable_shards[:2]
    )
    assert not np.array_equal(shard0, shard1)
    assert np.isfinite(float(metrics["critic_loss"]))


def test_dp_sac_train_step_runs_and_replicates():
    """SAC fused trainer under dp: same layout as DDPG plus replicated
    log-α; two steps run with finite losses and replicated params."""
    from actor_critic_tpu.algos import sac
    from actor_critic_tpu.envs import make_point_mass
    from actor_critic_tpu.parallel import sac_state_specs

    env = make_point_mass()
    cfg = sac.SACConfig(
        num_envs=16, steps_per_iter=4, updates_per_iter=2,
        buffer_capacity=512, batch_size=8, warmup_steps=0, hidden=(16,),
    )
    mesh = _mesh()
    state = sac.init_state(env, cfg, jax.random.key(0))
    state = distribute_state(state, mesh, sac_state_specs())
    step = make_dp_train_step(
        sac.make_train_step(env, cfg, axis_name=DP_AXIS),
        mesh,
        sac_state_specs(),
    )
    state, metrics = step(state)
    jax.block_until_ready(state)  # see note in test_dp_learning_two_state
    state, metrics = step(state)
    jax.block_until_ready(state)

    for tree in (state.learner.actor_params, state.learner.critic_params):
        leaf = jax.tree.leaves(tree)[0]
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)
    # log_alpha is a replicated scalar updated by pmean-ed gradients.
    ashards = [
        np.asarray(s.data) for s in state.learner.log_alpha.addressable_shards
    ]
    for s in ashards[1:]:
        np.testing.assert_array_equal(ashards[0], s)
    assert np.isfinite(float(metrics["critic_loss"]))
    assert np.isfinite(float(metrics["alpha"]))


def test_dp_impala_train_step_runs_and_replicates():
    """IMPALA's state (with stale actor params) shards and stays replicated
    across the dp mesh; staleness refresh happens identically per device."""
    from actor_critic_tpu.algos import impala
    from actor_critic_tpu.parallel import impala_state_specs

    env = make_two_state_mdp()
    cfg = impala.ImpalaConfig(
        num_envs=16, rollout_steps=4, hidden=(16,), actor_refresh_every=2
    )
    mesh = _mesh()
    state = impala.init_state(env, cfg, jax.random.key(0))
    state = distribute_state(state, mesh, impala_state_specs())
    step = make_dp_train_step(
        impala.make_train_step(env, cfg, axis_name=DP_AXIS),
        mesh,
        impala_state_specs(),
    )
    state, metrics = step(state)
    jax.block_until_ready(state)  # see note in test_dp_learning_two_state
    state, metrics = step(state)
    jax.block_until_ready(state)

    for tree in (state.params, state.actor_params):
        leaf = jax.tree.leaves(tree)[0]
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)
    # Step 2 is a refresh boundary ⇒ actor == learner params.
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        state.params,
        state.actor_params,
    )
    assert np.isfinite(float(metrics["loss"]))
