"""Tier-1 wiring for scripts/check_warmup_registry.py (ISSUE 4): a
`jax.jit` entry point added to algos/ or models/ without an AOT warmup
registration (or an explicit exemption with a reason) must fail fast in
CI, not resurface as first-dispatch compile latency weeks later."""

import importlib.util
from pathlib import Path


def _load_lint():
    spec = importlib.util.spec_from_file_location(
        "check_warmup_registry",
        Path(__file__).parent.parent / "scripts" / "check_warmup_registry.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_registry_covers_every_jit_entry_point(capsys):
    lint = _load_lint()
    assert lint.main([]) == 0, capsys.readouterr().err


def test_lint_detects_unregistered_sites(tmp_path):
    """The AST scanner must see direct calls, decorators, and
    partial(jax.jit, ...) forms, keyed by enclosing top-level def."""
    lint = _load_lint()
    src = (
        "import jax\n"
        "from functools import partial\n"
        "def make_thing(cfg):\n"
        "    @partial(jax.jit, donate_argnums=0)\n"
        "    def f(x):\n"
        "        return x\n"
        "    return f\n"
        "def make_other(cfg):\n"
        "    return jax.jit(lambda x: x)\n"
    )
    p = tmp_path / "newalgo.py"
    p.write_text(src)
    sites = lint.jit_sites(str(p))
    assert sorted(fn for fn, _ in sites) == ["make_other", "make_thing"]
