"""Tier-1 wiring for perfsan (ISSUE 15 runtime half).

Mirrors test_racesan/test_fleetsan/test_numsan's layers: (1) the quick
profile sweeps green against the COMMITTED perf_budgets.json, (2) the
counters are structural — two runs of the same program measure
identical actuals, (3) a tightened budget is caught (the meter is not
vacuous), (4) both reverted-regression modes are caught
deterministically on every run, (5) the CLI's exit codes stay distinct
(0 green / 1 violation-or-detection / 2 crash).

The exercisers compile tiny REAL programs (the fixture idiom numsan
uses), so this module is JAX_PLATFORMS=cpu-safe; the heavyweight
mixture-fleet program is exercised once and reused.
"""

import importlib.util
import json
from pathlib import Path

import numpy as np
import pytest

from actor_critic_tpu.analysis import perfsan

REPO = Path(__file__).parent.parent
MANIFEST = REPO / "perf_budgets.json"


def _load_cli():
    spec = importlib.util.spec_from_file_location(
        "perfsan_cli", REPO / "scripts" / "perfsan.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _budgets():
    return perfsan.load_manifest(str(MANIFEST))


# ---------------------------------------------------------------------------
# the committed manifest is green for every steady-state program
# ---------------------------------------------------------------------------


def test_manifest_is_committed_and_well_formed():
    budgets = _budgets()
    for name in perfsan.PROGRAMS:
        assert name in budgets, f"{name} missing a committed budget"
        for key in perfsan.BUDGET_KEYS:
            assert key in budgets[name], f"{name} missing {key}"
    # the device plane's actor-side enqueue budget rides along
    assert "ppo_update_device.enqueue" in budgets


def test_ppo_update_host_within_budget():
    report = perfsan.run_program("ppo_update_host", _budgets())
    c = report["counters"]
    # the host plane PAYS a per-block upload — budgeted, nonzero
    assert c.transferred_bytes > 0
    assert c.recompiles == 0


def test_ppo_update_device_within_budget_and_zero_transfer():
    report = perfsan.run_program("ppo_update_device", _budgets())
    c = report["counters"]
    # the PR 13 contract, metered: ONE program, ONE explicit transfer
    # (the staged slot index scalar), 4 bytes, zero recompiles
    assert c.dispatches == 1
    assert c.transfers == 1
    assert c.transferred_bytes == 4
    assert c.recompiles == 0
    # the actor-side enqueue moves the encoded bytes instead
    assert report["enqueue"].transferred_bytes >= report[
        "enqueue_bytes_per_block"
    ]
    assert report["enqueue_bytes_per_block"] < report[
        "host_bytes_per_block"
    ]


def test_ppo_update_fused_within_budget():
    """ISSUE 19: the fused consume (gather + decode + advantages via the
    common.gae_targets seam + update, correction='none') meters the SAME
    one-program contract as the device plane — the advantage scan costs
    no extra dispatch, crossing, or recompile."""
    report = perfsan.run_program("ppo_update_fused", _budgets())
    c = report["counters"]
    assert c.dispatches == 1
    assert c.transfers == 1
    assert c.transferred_bytes == 4
    assert c.recompiles == 0


def test_offpolicy_ingest_within_budget():
    report = perfsan.run_program("offpolicy_ingest", _budgets())
    assert report["counters"].dispatches == 1
    assert report["counters"].recompiles == 0


def test_serving_dispatch_swap_never_recompiles():
    report = perfsan.run_program("serving_dispatch", _budgets())
    c = report["counters"]
    assert c.dispatches == 1  # one program per act, every bucket
    assert c.recompiles == 0  # including the act AFTER the hot-swap
    assert c.transfers == 2  # device_put obs in, device_get actions out


def test_serving_overlap_within_budget():
    """ISSUE 17: the overlapped act path (max_inflight flight workers
    dispatching off the 1-deep handoff) keeps the per-act serving
    budget — one dispatch, the two explicit crossings, zero recompiles
    — with flight-thread work metered under the global transfer
    guard."""
    report = perfsan.run_program("serving_overlap", _budgets())
    c = report["counters"]
    assert c.dispatches == 1
    assert c.transfers == 2
    assert c.recompiles == 0


def test_serving_proxy_hop_is_all_zero():
    """ISSUE 17 leg b: the fleet-proxy relay carries NO device state —
    the whole proxied request meters zero dispatches, zero crossings,
    zero bytes, zero recompiles."""
    report = perfsan.run_program("serving_proxy_hop", _budgets())
    c = report["counters"]
    assert c.dispatches == 0
    assert c.transfers == 0 and c.transferred_bytes == 0
    assert c.recompiles == 0


def test_mixture_fleet_step_is_one_fused_program():
    report = perfsan.run_program("mixture_fleet_step", _budgets())
    c = report["counters"]
    assert c.dispatches == 1
    assert c.transfers == 0 and c.transferred_bytes == 0
    assert c.recompiles == 0


# ---------------------------------------------------------------------------
# determinism: the counters are structural
# ---------------------------------------------------------------------------


def test_counters_are_identical_run_to_run():
    a = perfsan.exercise_ppo_update_device(blocks=2)
    b = perfsan.exercise_ppo_update_device(blocks=2)
    assert [c.as_dict() for c in a["per_block"]] == [
        c.as_dict() for c in b["per_block"]
    ]
    # and across seeds: the budgets gate structure, not data
    c = perfsan.exercise_ppo_update_device(blocks=2, seed=7)
    assert a["counters"].as_dict() == c["counters"].as_dict()


# ---------------------------------------------------------------------------
# the meter is not vacuous: a tightened budget trips
# ---------------------------------------------------------------------------


def test_tightened_budget_is_a_violation():
    budgets = {
        "ppo_update_host": {
            "max_dispatches_per_block": 0,
            "max_transfers_per_block": 0,
            "max_transferred_bytes_per_block": 0,
            "max_recompiles": 0,
        }
    }
    with pytest.raises(perfsan.PerfSanError, match="BUDGET VIOLATION"):
        perfsan.run_program("ppo_update_host", budgets)


def test_missing_program_budget_is_a_violation():
    with pytest.raises(perfsan.PerfSanError, match="no budget entry"):
        perfsan.check_budget("brand_new_program", perfsan.Counters(), {})


def test_malformed_manifest_is_a_crash_not_a_detection(tmp_path):
    p = tmp_path / "perf_budgets.json"
    p.write_text("{not json")
    with pytest.raises(perfsan.ManifestError):
        perfsan.load_manifest(str(p))
    with pytest.raises(perfsan.ManifestError):
        perfsan.load_manifest(str(tmp_path / "missing.json"))


def test_manifest_key_typos_are_refused(tmp_path):
    """A misspelled or dropped max_* key would silently UN-GATE that
    counter forever — load_manifest must refuse both loudly."""
    base = {
        "max_dispatches_per_block": 1,
        "max_transfers_per_block": 1,
        "max_transferred_bytes_per_block": 4,
        "max_recompiles": 0,
    }
    p = tmp_path / "perf_budgets.json"
    typo = dict(base)
    typo["max_transfer_per_block"] = typo.pop("max_transfers_per_block")
    p.write_text(json.dumps({"version": 1, "programs": {"x": typo}}))
    with pytest.raises(perfsan.ManifestError, match="unknown key"):
        perfsan.load_manifest(str(p))
    dropped = dict(base)
    del dropped["max_recompiles"]
    p.write_text(json.dumps({"version": 1, "programs": {"x": dropped}}))
    with pytest.raises(perfsan.ManifestError, match="missing budget"):
        perfsan.load_manifest(str(p))


# ---------------------------------------------------------------------------
# reverted modes: caught deterministically on EVERY run
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("run", [0, 1])
def test_reverted_host_gather_detected(run):
    with pytest.raises(perfsan.PerfSanError):
        perfsan.run_reverted("host-gather", str(MANIFEST))


@pytest.mark.parametrize("run", [0, 1])
def test_reverted_unfused_detected(run):
    """Splitting the advantage program back out of the fused consume
    (the pre-ISSUE-19 two-dispatch shape) trips the dispatch budget on
    every run."""
    with pytest.raises(
        perfsan.PerfSanError, match="max_dispatches_per_block"
    ):
        perfsan.run_reverted("unfused", str(MANIFEST))


def test_reverted_uncommit_detected():
    with pytest.raises(
        perfsan.PerfSanError, match="max_recompiles"
    ):
        perfsan.run_reverted("uncommit", str(MANIFEST))


def test_measure_restores_all_seams():
    """The measure() context must restore the dispatch hook and the
    four transfer seams even when the block raises — a leaked patch
    would meter (and slow) every later dispatch in the process."""
    import jax
    import jax.numpy as jnp
    from jaxlib import xla_extension as xe

    orig = (
        jax.device_put, jax.device_get, jnp.array, jnp.asarray,
        xe.jax_jit.global_state().post_hook,
    )
    with pytest.raises(RuntimeError):
        with perfsan.measure():
            raise RuntimeError("boom")
    now = (
        jax.device_put, jax.device_get, jnp.array, jnp.asarray,
        xe.jax_jit.global_state().post_hook,
    )
    assert now == orig


# ---------------------------------------------------------------------------
# CLI exit codes
# ---------------------------------------------------------------------------


def test_cli_exit_codes(capsys, tmp_path):
    cli = _load_cli()
    # one cheap program green against the committed manifest
    assert cli.main(["--program", "serving_dispatch"]) == 0
    # tightened manifest -> violation (exit 1)
    tight = {
        "version": 1,
        "programs": {
            "serving_dispatch": {
                "max_dispatches_per_block": 0,
                "max_transfers_per_block": 0,
                "max_transferred_bytes_per_block": 0,
                "max_recompiles": 0,
            }
        },
    }
    p = tmp_path / "tight.json"
    p.write_text(json.dumps(tight))
    assert cli.main(
        ["--program", "serving_dispatch", "--manifest", str(p)]
    ) == 1
    # missing manifest -> crash (exit 2), never a detection
    assert cli.main(
        ["--program", "serving_dispatch", "--manifest",
         str(tmp_path / "missing.json")]
    ) == 2
    # unknown program -> crash
    assert cli.main(["--program", "no-such"]) == 2
    # --revert and --program are exclusive
    assert cli.main(
        ["--revert", "uncommit", "--program", "serving_dispatch"]
    ) == 2
    capsys.readouterr()


def test_cli_revert_modes_exit_one(capsys):
    cli = _load_cli()
    assert cli.main(["--revert", "uncommit"]) == 1
    out = capsys.readouterr()
    assert "VIOLATION DETECTED" in out.err
    assert cli.main(["--revert", "unfused"]) == 1
    out = capsys.readouterr()
    assert "VIOLATION DETECTED" in out.err


def test_cli_json_and_out(capsys, tmp_path):
    cli = _load_cli()
    out_path = tmp_path / "actuals.json"
    rc = cli.main(
        ["--program", "serving_dispatch", "--json", "--out",
         str(out_path)]
    )
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["programs"]["serving_dispatch"]["actuals"][
        "recompiles"
    ] == 0
    on_disk = json.loads(out_path.read_text())
    assert on_disk == payload
