"""Pallas scan kernels vs. the lax.scan golden implementations
(interpret mode on the CPU test backend; compiled path exercised on TPU
by bench/ and the fused trainers)."""

import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.ops import pallas_scan, returns

GAMMA, LAM = 0.99, 0.95


@pytest.fixture(scope="module")
def traj():
    rng = np.random.default_rng(0)
    T, E = 17, 512  # odd T; E hits one full block
    rewards = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    values = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    dones = jnp.asarray(rng.random(size=(T, E)) < 0.1, jnp.float32)
    bootstrap = jnp.asarray(rng.normal(size=(E,)), jnp.float32)
    return rewards, values, dones, bootstrap


def test_gae_matches_golden(traj):
    rewards, values, dones, bootstrap = traj
    adv_g, ret_g = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    adv, ret = pallas_scan.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ret), np.asarray(ret_g), rtol=1e-6, atol=1e-6)


def test_gae_multi_block(traj):
    """E larger than one block → grid > 1, blocks must not interact."""
    rewards, values, dones, bootstrap = traj
    r2 = jnp.concatenate([rewards, rewards * 2.0], axis=1)
    v2 = jnp.concatenate([values, values * -1.0], axis=1)
    d2 = jnp.concatenate([dones, dones], axis=1)
    b2 = jnp.concatenate([bootstrap, bootstrap], axis=0)
    adv_g, _ = returns.gae(r2, v2, d2, b2, GAMMA, LAM)
    adv, _ = pallas_scan.gae(r2, v2, d2, b2, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-6, atol=1e-6)


def test_gae_small_batch_fallback_block(traj):
    """E not divisible by the default block → smaller power-of-two block."""
    rewards, values, dones = (a[:, :96] for a in traj[:3])
    bootstrap = traj[3][:96]
    adv_g, _ = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    adv, _ = pallas_scan.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-6, atol=1e-6)


def test_gae_non_2d_falls_back(traj):
    rewards, values, dones, bootstrap = traj
    adv, _ = pallas_scan.gae(rewards[:, 0], values[:, 0], dones[:, 0],
                             bootstrap[0], GAMMA, LAM)
    adv_g, _ = returns.gae(rewards[:, 0], values[:, 0], dones[:, 0],
                           bootstrap[0], GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-6, atol=1e-6)


def test_vtrace_cbar_above_rhobar(traj):
    """c clips the RAW ratio: c_bar > rho_bar must still match golden
    (regression: kernel once derived c from the rho_bar-clipped rho)."""
    rewards, values, dones, bootstrap = traj
    rng = np.random.default_rng(5)
    tlp = jnp.asarray(rng.normal(size=rewards.shape), jnp.float32)
    blp = jnp.asarray(rng.normal(size=rewards.shape), jnp.float32)
    golden = returns.vtrace(tlp, blp, rewards, values, dones, bootstrap,
                            GAMMA, rho_bar=1.0, c_bar=2.0, lam=0.9)
    got = pallas_scan.vtrace(tlp, blp, rewards, values, dones, bootstrap,
                             GAMMA, rho_bar=1.0, c_bar=2.0, lam=0.9)
    for name in ("vs", "pg_advantages", "clipped_rhos"):
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(golden, name)),
            rtol=1e-5, atol=1e-5, err_msg=name,
        )


def test_gae_long_T_shrinks_block_or_falls_back(traj):
    """T large enough to force a narrow block (or the lax.scan fallback)
    still produces golden results."""
    rng = np.random.default_rng(3)
    T, E = 4096, 128
    rewards = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    values = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    dones = jnp.asarray(rng.random(size=(T, E)) < 0.02, jnp.float32)
    bootstrap = jnp.asarray(rng.normal(size=(E,)), jnp.float32)
    adv_g, _ = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    adv, _ = pallas_scan.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-5, atol=1e-5)


def test_vtrace_matches_golden(traj):
    rewards, values, dones, bootstrap = traj
    rng = np.random.default_rng(1)
    tlp = jnp.asarray(rng.normal(size=rewards.shape) * 0.3, jnp.float32)
    blp = jnp.asarray(rng.normal(size=rewards.shape) * 0.3, jnp.float32)
    golden = returns.vtrace(tlp, blp, rewards, values, dones, bootstrap,
                            GAMMA, rho_bar=1.0, c_bar=1.0, lam=0.9)
    got = pallas_scan.vtrace(tlp, blp, rewards, values, dones, bootstrap,
                             GAMMA, rho_bar=1.0, c_bar=1.0, lam=0.9)
    for name in ("vs", "pg_advantages", "clipped_rhos"):
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(golden, name)),
            rtol=1e-5, atol=1e-6, err_msg=name,
        )


def test_kernel_block_engagement():
    """kernel_block must report exactly when the kernels engage vs fall
    back — benches and future callers rely on it to avoid attributing
    lax.scan timings to Pallas (the T=2048 V-trace fallback burned the
    round-3 bench once already)."""
    from actor_critic_tpu.ops import pallas_scan as ps

    # 11-array V-trace: T=2048 exceeds the VMEM tile budget → fallback.
    assert ps.kernel_block("vtrace", 2048, 256) == 0
    # T=1024 still fits a 128-lane tile.
    assert ps.kernel_block("vtrace", 1024, 256) == 128
    # 7-array GAE fits at T=2048.
    assert ps.kernel_block("gae", 2048, 256) == 128
    # Headline trainer shape: full default tile.
    assert ps.kernel_block("gae", 32, 4096) == 512
    # E not a multiple of 128 → no legal tile.
    assert ps.kernel_block("gae", 32, 100) == 0
