"""Pallas scan kernels vs. the lax.scan golden implementations
(interpret mode on the CPU test backend; compiled path exercised on TPU
by bench/ and the fused trainers)."""

import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.ops import pallas_scan, returns

GAMMA, LAM = 0.99, 0.95


@pytest.fixture(scope="module")
def traj():
    rng = np.random.default_rng(0)
    T, E = 17, 512  # odd T; E hits one full block
    rewards = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    values = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    dones = jnp.asarray(rng.random(size=(T, E)) < 0.1, jnp.float32)
    bootstrap = jnp.asarray(rng.normal(size=(E,)), jnp.float32)
    return rewards, values, dones, bootstrap


def test_gae_matches_golden(traj):
    rewards, values, dones, bootstrap = traj
    adv_g, ret_g = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    adv, ret = pallas_scan.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ret), np.asarray(ret_g), rtol=1e-6, atol=1e-6)


def test_gae_multi_block(traj):
    """E larger than one block → grid > 1, blocks must not interact."""
    rewards, values, dones, bootstrap = traj
    r2 = jnp.concatenate([rewards, rewards * 2.0], axis=1)
    v2 = jnp.concatenate([values, values * -1.0], axis=1)
    d2 = jnp.concatenate([dones, dones], axis=1)
    b2 = jnp.concatenate([bootstrap, bootstrap], axis=0)
    adv_g, _ = returns.gae(r2, v2, d2, b2, GAMMA, LAM)
    adv, _ = pallas_scan.gae(r2, v2, d2, b2, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-6, atol=1e-6)


def test_gae_small_batch_lane_padded(traj):
    """E below one 128-lane tile → zero-padded to one tile, sliced back;
    the kernel must ENGAGE (ISSUE 19), not silently fall back."""
    rewards, values, dones = (a[:, :96] for a in traj[:3])
    bootstrap = traj[3][:96]
    assert pallas_scan.kernel_block("gae", rewards.shape[0], 96) == 128
    adv_g, _ = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    adv, _ = pallas_scan.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    assert adv.shape == rewards.shape
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-6, atol=1e-6)


def test_gae_non_2d_falls_back(traj):
    rewards, values, dones, bootstrap = traj
    adv, _ = pallas_scan.gae(rewards[:, 0], values[:, 0], dones[:, 0],
                             bootstrap[0], GAMMA, LAM)
    adv_g, _ = returns.gae(rewards[:, 0], values[:, 0], dones[:, 0],
                           bootstrap[0], GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-6, atol=1e-6)


def test_vtrace_cbar_above_rhobar(traj):
    """c clips the RAW ratio: c_bar > rho_bar must still match golden
    (regression: kernel once derived c from the rho_bar-clipped rho)."""
    rewards, values, dones, bootstrap = traj
    rng = np.random.default_rng(5)
    tlp = jnp.asarray(rng.normal(size=rewards.shape), jnp.float32)
    blp = jnp.asarray(rng.normal(size=rewards.shape), jnp.float32)
    golden = returns.vtrace(tlp, blp, rewards, values, dones, bootstrap,
                            GAMMA, rho_bar=1.0, c_bar=2.0, lam=0.9)
    got = pallas_scan.vtrace(tlp, blp, rewards, values, dones, bootstrap,
                             GAMMA, rho_bar=1.0, c_bar=2.0, lam=0.9)
    for name in ("vs", "pg_advantages", "clipped_rhos"):
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(golden, name)),
            rtol=1e-5, atol=1e-5, err_msg=name,
        )


def test_gae_long_T_shrinks_block_or_falls_back(traj):
    """T large enough to force a narrow block (or the lax.scan fallback)
    still produces golden results."""
    rng = np.random.default_rng(3)
    T, E = 4096, 128
    rewards = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    values = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    dones = jnp.asarray(rng.random(size=(T, E)) < 0.02, jnp.float32)
    bootstrap = jnp.asarray(rng.normal(size=(E,)), jnp.float32)
    adv_g, _ = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    adv, _ = pallas_scan.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-5, atol=1e-5)


def test_vtrace_matches_golden(traj):
    rewards, values, dones, bootstrap = traj
    rng = np.random.default_rng(1)
    tlp = jnp.asarray(rng.normal(size=rewards.shape) * 0.3, jnp.float32)
    blp = jnp.asarray(rng.normal(size=rewards.shape) * 0.3, jnp.float32)
    golden = returns.vtrace(tlp, blp, rewards, values, dones, bootstrap,
                            GAMMA, rho_bar=1.0, c_bar=1.0, lam=0.9)
    got = pallas_scan.vtrace(tlp, blp, rewards, values, dones, bootstrap,
                             GAMMA, rho_bar=1.0, c_bar=1.0, lam=0.9)
    for name in ("vs", "pg_advantages", "clipped_rhos"):
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(golden, name)),
            rtol=1e-5, atol=1e-6, err_msg=name,
        )


def test_kernel_block_engagement():
    """kernel_block must report exactly when the kernels engage vs fall
    back — benches and future callers rely on it to avoid attributing
    lax.scan timings to Pallas (the T=2048 V-trace fallback burned the
    round-3 bench once already)."""
    from actor_critic_tpu.ops import pallas_scan as ps

    # 11-array V-trace: T=2048 exceeds the VMEM tile budget → fallback.
    assert ps.kernel_block("vtrace", 2048, 256) == 0
    # T=1024 still fits a 128-lane tile.
    assert ps.kernel_block("vtrace", 1024, 256) == 128
    # 7-array GAE fits at T=2048.
    assert ps.kernel_block("gae", 2048, 256) == 128
    # λ-returns ride the GAE kernel, so they price identically.
    assert ps.kernel_block("lambda", 2048, 256) == 128
    # Headline trainer shape: full default tile.
    assert ps.kernel_block("gae", 32, 4096) == 512
    # Ragged/small E lane-pads to the next 128 multiple (ISSUE 19):
    # the kernel now ENGAGES instead of silently falling back.
    assert ps.kernel_block("gae", 32, 100) == 128
    assert ps.kernel_block("gae", 32, 8) == 128
    assert ps.kernel_block("vtrace", 64, 200) == 256  # pads 200 → 256, one block
    # Only an impossible T still reports the lax.scan fallback.
    assert ps.kernel_block("gae", 1 << 20, 256) == 0


# ---------------------------------------------------------------------------
# ISSUE 19 boundary-shape golden parity: T=1, E below one lane tile,
# non-divisible E/block, and done-at-t0, for all three fused scans.
# ---------------------------------------------------------------------------


def _rand_batch(T, E, seed=7):
    rng = np.random.default_rng(seed)
    rewards = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    values = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    dones = jnp.asarray(rng.random(size=(T, E)) < 0.15, jnp.float32)
    bootstrap = jnp.asarray(rng.normal(size=(E,)), jnp.float32)
    return rewards, values, dones, bootstrap


@pytest.mark.parametrize(
    "T,E",
    [(1, 128), (1, 7), (5, 96), (3, 300), (17, 640)],
    ids=["T1-tile", "T1-tiny", "E-sub-tile", "E-ragged", "E-nondiv-block"],
)
def test_boundary_shapes_gae_lambda_golden(T, E):
    rewards, values, dones, bootstrap = _rand_batch(T, E)
    adv_g, ret_g = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    adv, ret = pallas_scan.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ret), np.asarray(ret_g), rtol=1e-6, atol=1e-6)
    lam_g = returns.lambda_returns(rewards, values, dones, bootstrap, GAMMA, LAM)
    lam_k = pallas_scan.lambda_returns(rewards, values, dones, bootstrap, GAMMA, LAM)
    assert lam_k.shape == (T, E)
    np.testing.assert_allclose(np.asarray(lam_k), np.asarray(lam_g), rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize(
    "T,E", [(1, 128), (1, 7), (5, 96), (3, 300)],
    ids=["T1-tile", "T1-tiny", "E-sub-tile", "E-ragged"],
)
def test_boundary_shapes_vtrace_golden(T, E):
    rewards, values, dones, bootstrap = _rand_batch(T, E, seed=11)
    rng = np.random.default_rng(13)
    tlp = jnp.asarray(rng.normal(size=(T, E)) * 0.3, jnp.float32)
    blp = jnp.asarray(rng.normal(size=(T, E)) * 0.3, jnp.float32)
    golden = returns.vtrace(tlp, blp, rewards, values, dones, bootstrap,
                            GAMMA, rho_bar=1.0, c_bar=1.0, lam=0.9)
    got = pallas_scan.vtrace(tlp, blp, rewards, values, dones, bootstrap,
                             GAMMA, rho_bar=1.0, c_bar=1.0, lam=0.9)
    for name in ("vs", "pg_advantages", "clipped_rhos"):
        assert getattr(got, name).shape == (T, E)
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(golden, name)),
            rtol=1e-5, atol=1e-6, err_msg=name,
        )


def test_done_at_t0_golden():
    """done on the very first row must cut the recurrence exactly as the
    lax reference does (the carry enters the loop non-zero)."""
    T, E = 4, 128
    rewards, values, _, bootstrap = _rand_batch(T, E, seed=17)
    dones = jnp.zeros((T, E), jnp.float32).at[0, :].set(1.0)
    adv_g, ret_g = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    adv, ret = pallas_scan.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ret), np.asarray(ret_g), rtol=1e-6, atol=1e-6)
    got = pallas_scan.vtrace(values * 0.1, rewards * 0.1, rewards, values,
                             dones, bootstrap, GAMMA)
    golden = returns.vtrace(values * 0.1, rewards * 0.1, rewards, values,
                            dones, bootstrap, GAMMA)
    np.testing.assert_allclose(np.asarray(got.vs), np.asarray(golden.vs),
                               rtol=1e-5, atol=1e-6)


def test_lambda_returns_auto_dispatch(traj):
    """lambda_returns_auto falls back to the lax reference off-TPU and
    matches it bitwise there (the interpret-mode kernel is test-only)."""
    rewards, values, dones, bootstrap = traj
    got = pallas_scan.lambda_returns_auto(rewards, values, dones, bootstrap,
                                          GAMMA, LAM)
    ref = returns.lambda_returns(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
