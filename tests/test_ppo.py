"""PPO unit + learning tests (SURVEY.md §4): ratio/clip edge cases against
hand-computed values, and convergence on analytic envs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.algos import ppo
from actor_critic_tpu.envs import make_point_mass, make_two_state_mdp


def _const_batch(B=8):
    return ppo.PPOBatch(
        obs=jnp.zeros((B, 2)),
        action=jnp.zeros((B,), jnp.int32),
        log_prob_old=jnp.zeros((B,)),
        value_old=jnp.zeros((B,)),
        advantage=jnp.ones((B,)),
        ret=jnp.zeros((B,)),
    )


def test_ppo_loss_clip_edges():
    """Hand-check the clipped surrogate on controlled ratios."""
    cfg = ppo.PPOConfig(clip_eps=0.2, normalize_adv=False, vf_clip=0.0,
                        entropy_coef=0.0, value_coef=0.0)

    # Fake apply_fn: log_prob = theta (scalar param broadcast), value = 0.
    class FakeDist:
        def __init__(self, lp):
            self._lp = lp
        def log_prob(self, a):
            return jnp.broadcast_to(self._lp, a.shape)
        def entropy(self):
            return jnp.zeros(())

    def apply_fn(theta, obs):
        return FakeDist(theta), jnp.zeros(obs.shape[0])

    batch = _const_batch()

    # positive advantage: ratio above 1+eps must be clipped -> grad 0
    loss_fn = lambda th: ppo.ppo_loss(th, apply_fn, batch, cfg)[0]
    theta_hi = jnp.log(1.5)  # ratio 1.5 > 1.2
    g = jax.grad(loss_fn)(theta_hi)
    np.testing.assert_allclose(float(g), 0.0, atol=1e-6)
    # loss value equals -clip(1.5 -> 1.2)*adv = -1.2
    np.testing.assert_allclose(float(loss_fn(theta_hi)), -1.2, rtol=1e-5)

    # ratio inside the clip band: gradient flows (= -ratio)
    theta_in = jnp.log(1.1)
    g_in = jax.grad(loss_fn)(theta_in)
    np.testing.assert_allclose(float(g_in), -1.1, rtol=1e-5)

    # negative advantage, ratio below 1-eps: clipped -> grad 0
    batch_neg = batch._replace(advantage=-jnp.ones(8))
    loss_fn_neg = lambda th: ppo.ppo_loss(th, apply_fn, batch_neg, cfg)[0]
    theta_lo = jnp.log(0.5)
    g_neg = jax.grad(loss_fn_neg)(theta_lo)
    np.testing.assert_allclose(float(g_neg), 0.0, atol=1e-6)
    np.testing.assert_allclose(float(loss_fn_neg(theta_lo)), 0.8, rtol=1e-5)


def test_ppo_value_clip():
    cfg = ppo.PPOConfig(vf_clip=0.1, normalize_adv=False, entropy_coef=0.0,
                        value_coef=1.0, clip_eps=0.2)

    class ZeroDist:
        def log_prob(self, a):
            return jnp.zeros(a.shape)
        def entropy(self):
            return jnp.zeros(())

    def apply_fn(v, obs):
        return ZeroDist(), jnp.broadcast_to(v, (obs.shape[0],))

    batch = _const_batch()._replace(
        value_old=jnp.zeros((8,)), ret=jnp.ones((8,)), advantage=jnp.zeros((8,))
    )
    # v = 0.5: clipped to 0.1; loss = 0.5*max((0.5-1)^2, (0.1-1)^2) = 0.5*0.81
    loss, m = ppo.ppo_loss(jnp.asarray(0.5), apply_fn, batch, cfg)
    np.testing.assert_allclose(float(loss), 0.5 * 0.81, rtol=1e-5)


def test_ppo_update_shapes_and_determinism():
    env = make_two_state_mdp()
    cfg = ppo.PPOConfig(num_envs=8, rollout_steps=8, epochs=2,
                        num_minibatches=4, hidden=(16,))
    state = ppo.init_state(env, cfg, jax.random.key(0))
    step = jax.jit(ppo.make_train_step(env, cfg))
    s1, m1 = step(state)
    s2, m2 = step(state)
    # same input state => bitwise-identical result (determinism, SURVEY §4)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        s1.params,
        s2.params,
    )
    assert np.isfinite(float(m1["approx_kl"]))
    assert 0.0 <= float(m1["clip_frac"]) <= 1.0


def test_ppo_update_rejects_indivisible_batch():
    env = make_two_state_mdp()
    cfg = ppo.PPOConfig(num_envs=3, rollout_steps=3, num_minibatches=4, hidden=(8,))
    state = ppo.init_state(env, cfg, jax.random.key(0))
    with pytest.raises(ValueError, match="minibatches"):
        ppo.make_train_step(env, cfg)(state)


def test_ppo_learns_two_state():
    env = make_two_state_mdp()
    cfg = ppo.PPOConfig(
        num_envs=16, rollout_steps=16, epochs=4, num_minibatches=4,
        lr=3e-3, gamma=0.9, hidden=(32,), entropy_coef=0.001,
    )
    state = ppo.init_state(env, cfg, jax.random.key(1))
    step = jax.jit(ppo.make_train_step(env, cfg), donate_argnums=0)
    for _ in range(60):
        state, metrics = step(state)
    net = ppo.make_network(env.spec, cfg)
    dist, v = net.apply(state.params, jnp.eye(2))
    p1 = jax.nn.softmax(dist.logits)[:, 1]
    assert float(p1.min()) > 0.9, f"PPO failed to learn: P(a=1)={p1}"
    # critic fixed point with truncation bootstrap is 1/(1-gamma) = 10
    np.testing.assert_allclose(np.asarray(v), [10.0, 10.0], rtol=0.15)


@pytest.mark.slow
def test_ppo_learns_cartpole():
    """CartPole learning test (SURVEY.md §4: 'CartPole-v1 A2C/PPO reach
    reward >=195 within a step budget'). Runs the EXACT shipped
    ppo_cartpole config for 30 iterations (the TPU evidence runs —
    results/cartpole_solve_seed*.json — solve >=475 in <=35 iterations
    on 3 seeds); the best greedy eval over iterations 20/25/30 must
    clear 400 on CPU."""
    from actor_critic_tpu.config import PRESETS
    from actor_critic_tpu.envs import make_cartpole

    env = make_cartpole()
    cfg = PRESETS["ppo_cartpole"].config  # the exact shipped config
    state = ppo.init_state(env, cfg, jax.random.key(0))
    step = jax.jit(ppo.make_train_step(env, cfg), donate_argnums=0)
    eval_fn = jax.jit(ppo.make_eval_fn(env, cfg), static_argnums=(2, 3))
    best = 0.0
    for it in range(30):
        state, metrics = step(state)
        if it + 1 in (20, 25, 30):  # greedy eval oscillates; take the best
            best = max(best, float(eval_fn(state, jax.random.key(1), 32, 512)))
    assert best >= 400.0, f"CartPole not learned: best greedy eval {best}"


@pytest.mark.slow
def test_ppo_learns_point_mass_continuous():
    env = make_point_mass()
    cfg = ppo.PPOConfig(
        num_envs=32, rollout_steps=16, epochs=4, num_minibatches=4,
        lr=3e-3, hidden=(32, 32), entropy_coef=0.0,
    )
    state = ppo.init_state(env, cfg, jax.random.key(2))
    step = jax.jit(ppo.make_train_step(env, cfg), donate_argnums=0)
    for _ in range(300):
        state, metrics = step(state)
    # verified convergence profile: ema ≈ -0.12 at 300 iters, policy mean ≈ -pos
    assert float(metrics["avg_return_ema"]) > -0.3


def test_ppo_update_unroll_equivalence():
    """`unroll=True` must be bit-for-bit the same math as the scanned
    loop nest — it exists purely as an XLA:CPU lowering workaround
    (convs inside scan bodies can't use the fast conv custom-call)."""
    import numpy as np

    from actor_critic_tpu.envs import make_pong

    env = make_pong(size=36)
    cfg = ppo.PPOConfig(num_envs=4, rollout_steps=4, epochs=2,
                        num_minibatches=2)
    net = ppo.make_network(env.spec, cfg)
    opt = ppo.make_optimizer(cfg)
    B = 16
    obs = jnp.asarray(
        np.random.default_rng(0).integers(0, 255, (B, 36, 36, 2)), jnp.uint8
    )
    batch = ppo.PPOBatch(
        obs=obs,
        action=jnp.zeros((B,), jnp.int32),
        log_prob_old=jnp.full((B,), -1.0),
        value_old=jnp.zeros((B,)),
        advantage=jnp.linspace(-1, 1, B),
        ret=jnp.linspace(0, 1, B),
    )
    params = net.init(jax.random.key(0), obs[:1])
    os0 = opt.init(params)
    key = jax.random.key(7)
    p1, _, m1 = ppo.ppo_update(
        params, os0, batch, key, net.apply, opt, cfg, unroll=False
    )
    p2, _, m2 = ppo.ppo_update(
        params, os0, batch, key, net.apply, opt, cfg, unroll=True
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        ),
        p1, p2,
    )
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)
    # And the default policy: CPU + pixels + small nest → unroll.
    assert ppo.should_unroll_update(env.spec, cfg) is True
    big = ppo.PPOConfig(epochs=10, num_minibatches=32)
    assert ppo.should_unroll_update(env.spec, big) is False
