"""Config/preset system + train.py CLI tests (SURVEY.md §5.6)."""

import json
import subprocess
import sys

import pytest

from actor_critic_tpu.config import (
    ALGO_CONFIGS,
    PRESETS,
    apply_overrides,
    parse_set_args,
    resolve,
)


def test_presets_cover_all_baseline_configs():
    """One preset per BASELINE.json:7-11 config (+ TD3 and A3C variants)."""
    algos = {p.algo for p in PRESETS.values()}
    assert {"a2c", "ppo", "ddpg", "td3", "sac", "impala", "a3c"} <= algos
    assert "a2c_cartpole" in PRESETS
    assert "ppo_halfcheetah" in PRESETS
    assert "sac_humanoid" in PRESETS
    assert "impala_pong" in PRESETS


def test_apply_overrides_coercion():
    from actor_critic_tpu.algos import a2c

    cfg = a2c.A2CConfig()
    out = apply_overrides(
        cfg,
        {"lr": "1e-4", "num_envs": "128", "hidden": "32,32,32",
         "normalize_adv": "true"},
    )
    assert out.lr == 1e-4
    assert out.num_envs == 128
    assert out.hidden == (32, 32, 32)
    assert out.normalize_adv is True
    assert cfg.lr != out.lr  # frozen original untouched


def test_apply_overrides_optional_and_errors():
    from actor_critic_tpu.algos import sac

    cfg = sac.SACConfig()
    out = apply_overrides(cfg, {"fixed_alpha": "0.2"})
    assert out.fixed_alpha == 0.2
    out = apply_overrides(out, {"fixed_alpha": "none"})
    assert out.fixed_alpha is None
    with pytest.raises(KeyError, match="no field"):
        apply_overrides(cfg, {"ler": "1e-4"})


def test_parse_set_args():
    assert parse_set_args(["a=1", "b=x=y"]) == {"a": "1", "b": "x=y"}
    with pytest.raises(ValueError):
        parse_set_args(["oops"])


def test_resolve_preset_with_override():
    pre = resolve("a2c_cartpole", None, None, {"num_envs": "64"})
    assert pre.algo == "a2c"
    assert pre.config.num_envs == 64


def test_resolve_algo_env_from_scratch():
    pre = resolve(None, "td3", "jax:point_mass", {})
    assert pre.config.twin_q is True  # td3_config applied
    pre = resolve(None, "a3c", "jax:pong", {})
    assert pre.config.correction == "none"
    with pytest.raises(ValueError):
        resolve(None, "a2c", None, {})
    with pytest.raises(KeyError):
        resolve("nope", None, None, {})


def test_algo_configs_constructible():
    for name, cls in ALGO_CONFIGS.items():
        cls()  # defaults must be valid


def test_env_kwargs_plumbing():
    """Preset env_kwargs flow to the env constructor; --env-set merges
    over them; changing the preset's env drops its env_kwargs."""
    from actor_critic_tpu.config import coerce_env_value, parse_env_set_args

    assert parse_env_set_args(["opp_skill=0.5", "frame_skip=4"]) == {
        "opp_skill": 0.5, "frame_skip": 4,
    }
    assert coerce_env_value("true") is True
    assert coerce_env_value("none") is None
    assert coerce_env_value("hello") == "hello"

    pre = resolve("impala_pong_learn", None, None, {})
    assert pre.env_kwargs == {"opp_skill": 0.5, "frame_skip": 4, "size": 36}
    pre = resolve("impala_pong_learn", None, None, {}, {"opp_skill": 0.75})
    assert pre.env_kwargs["opp_skill"] == 0.75
    assert pre.env_kwargs["frame_skip"] == 4
    # Pointing the preset at a different env keeps only CLI kwargs.
    pre = resolve("impala_pong_learn", None, "jax:cartpole", {}, {})
    assert pre.env_kwargs == {}

    import train as train_cli

    env, fused = train_cli.build_env(
        "jax:pong", "impala", pre.config, 0,
        env_kwargs={"opp_skill": 0.5, "frame_skip": 4, "size": 36},
    )
    assert fused
    assert env.spec.obs_shape[0] == 36  # size kwarg reached the maker
    with pytest.raises(SystemExit, match="bad --env-set"):
        train_cli.build_env(
            "jax:pong", "impala", pre.config, 0, env_kwargs={"nope": 1}
        )
    with pytest.raises(SystemExit, match="native"):
        train_cli.build_env(
            "native:CartPole-v1", "ppo", PRESETS["a2c_cartpole"].config, 0,
            env_kwargs={"x": 1},
        )


@pytest.mark.slow
def test_cli_end_to_end(tmp_path):
    """train.py runs a tiny fused job, writes JSONL + summary, resumes."""
    metrics = tmp_path / "m.jsonl"
    ckpt = tmp_path / "ck"
    cmd = [
        sys.executable, "train.py",
        "--algo", "a2c", "--env", "jax:two_state",
        "--iterations", "6", "--log-every", "2", "--quiet",
        "--set", "num_envs=8", "--set", "rollout_steps=4", "--set", "hidden=16",
        "--metrics", str(metrics),
        "--ckpt-dir", str(ckpt), "--save-every", "3",
    ]
    env = {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin"}
    import os

    env.update({k: v for k, v in os.environ.items() if k not in env})
    r = subprocess.run(cmd, capture_output=True, text=True, env=env, cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-2000:]
    rows = [json.loads(l) for l in metrics.read_text().splitlines()]
    assert rows and rows[-1]["iter"] == 6
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["env_steps"] == 6 * 8 * 4

    # Resume: checkpoint at 6 exists, asking for 8 runs only 7..8.
    assert cmd[6] == "--iterations"
    r2 = subprocess.run(
        cmd[:7] + ["8"] + cmd[8:] + ["--resume"],
        capture_output=True, text=True, env=env, cwd="/root/repo",
    )
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "resumed from iteration 6" in r2.stdout


def test_cli_replay_dtype_flag(tmp_path):
    """--replay-dtype threads into the off-policy config (fused DDPG
    run completes with a quantized ring) and refuses algos without
    replay storage."""
    import os

    env = {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
           "PATH": "/usr/bin:/bin"}
    env.update({k: v for k, v in os.environ.items() if k not in env})
    cmd = [
        sys.executable, "train.py",
        "--algo", "ddpg", "--env", "jax:point_mass",
        "--iterations", "2", "--log-every", "1", "--quiet",
        "--set", "num_envs=4", "--set", "steps_per_iter=2",
        "--set", "updates_per_iter=1", "--set", "buffer_capacity=64",
        "--set", "batch_size=4", "--set", "warmup_steps=0",
        "--set", "hidden=16",
        "--replay-dtype", "mixed",
        "--metrics", str(tmp_path / "m.jsonl"),
    ]
    r = subprocess.run(cmd, capture_output=True, text=True, env=env,
                       cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "replay_dtype': 'mixed'" in r.stdout  # config echo line

    bad = subprocess.run(
        [sys.executable, "train.py", "--algo", "a2c",
         "--env", "jax:two_state", "--iterations", "1",
         "--replay-dtype", "mixed"],
        capture_output=True, text=True, env=env, cwd="/root/repo",
    )
    assert bad.returncode != 0
    assert "no replay storage" in bad.stderr


@pytest.mark.slow
def test_cli_chunked_dispatch(tmp_path):
    """--chunk N scans N iterations per dispatch: same training
    trajectory as per-iteration dispatch (same seed, same step count),
    cadences snapped to chunk multiples, tail chunks + resume work."""
    import os

    env = {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin"}
    env.update({k: v for k, v in os.environ.items() if k not in env})
    base = [
        sys.executable, "train.py",
        "--algo", "a2c", "--env", "jax:two_state",
        "--iterations", "8", "--log-every", "2", "--quiet",
        "--set", "num_envs=8", "--set", "rollout_steps=4", "--set", "hidden=16",
    ]

    def run(extra, metrics):
        r = subprocess.run(
            base + ["--metrics", str(metrics)] + extra,
            capture_output=True, text=True, env=env, cwd="/root/repo",
        )
        assert r.returncode == 0, r.stderr[-2000:]
        rows = [json.loads(l) for l in metrics.read_text().splitlines()]
        return r, rows

    _, rows1 = run([], tmp_path / "m1.jsonl")
    r4, rows4 = run(["--chunk", "4"], tmp_path / "m4.jsonl")
    # Cadence snap is announced and applied: rows at chunk boundaries.
    assert "log_every 2 -> 4" in r4.stdout
    assert [row["iter"] for row in rows4] == [4, 8]
    # Identical trajectory: the scanned and per-iteration loops apply
    # the same train step the same number of times from the same seed.
    last1 = {k: v for k, v in rows1[-1].items()
             if isinstance(v, float) and k != "wall_s"}
    last4 = {k: v for k, v in rows4[-1].items()
             if isinstance(v, float) and k != "wall_s"}
    assert last1.keys() == last4.keys()
    for k in last1:
        assert last1[k] == pytest.approx(last4[k], rel=2e-3, abs=1e-5), k

    # Misaligned resume: 3 done per-iteration, resume chunked to 10.
    # The first chunk realigns to stride boundaries (k=1, then 4, then a
    # tail of 2), so the snapped cadences keep firing: without
    # realignment every boundary would sit at 3 mod 4 and no
    # intermediate log/save would ever trigger again.
    ckpt = tmp_path / "ck"
    run(["--iterations", "3", "--ckpt-dir", str(ckpt), "--save-every", "3"],
        tmp_path / "mr1.jsonl")
    rr, rows_r = run(
        ["--iterations", "10", "--ckpt-dir", str(ckpt), "--save-every", "4",
         "--chunk", "4", "--resume"],
        tmp_path / "mr2.jsonl",
    )
    assert "resumed from iteration 3" in rr.stdout
    assert [row["iter"] for row in rows_r] == [4, 8, 10]


def test_resolve_preset_with_different_algo_specializes():
    """--preset X --algo Y must swap in Y's *specialized* defaults, not the
    base dataclass (td3 without twin_q would silently run DDPG)."""
    pre = resolve("ddpg_walker2d", "td3", None, {})
    assert pre.config.twin_q is True
    pre = resolve("impala_pong", "a3c", None, {})
    assert pre.config.correction == "none"


@pytest.mark.parametrize("algo,normalized", [
    ("ppo", True), ("ddpg", False), ("td3", False), ("sac", False),
])
def test_build_env_normalization_policy(algo, normalized):
    """train.py's host pools normalize obs/rewards for on-policy PPO only.
    Off-policy replay must see RAW frames: running-stat normalization
    rescales early-stored transitions differently than fresh ones and the
    critic bootstraps across inconsistent frames (observed as the SAC
    Humanoid-v5 Q/alpha runaway). Regression-pins train.py build_env."""
    import train as train_cli

    cfg = ALGO_CONFIGS[algo](num_envs=1)
    pool, fused = train_cli.build_env("host:CartPole-v1", algo, cfg, seed=0)
    try:
        assert fused is False
        assert pool.normalizes_obs is normalized
    finally:
        pool.close()


def test_build_env_scale_actions_tristate():
    """--scale-actions threads through to BOTH env families; None keeps
    each env's own convention (host pools clip, jax:pendulum scales)."""
    import train as train_cli
    from actor_critic_tpu.algos import sac

    cfg = sac.SACConfig(num_envs=1)
    pool, _ = train_cli.build_env("host:Pendulum-v1", "sac", cfg, 0)
    assert pool.scales_actions is False  # None → pool default (clip)
    pool.close()
    pool, _ = train_cli.build_env(
        "host:Pendulum-v1", "sac", cfg, 0, scale_actions=True
    )
    assert pool.scales_actions is True
    pool.close()

    import jax
    import jax.numpy as jnp
    import numpy as np

    # jax:pendulum: None → scaled (env default); False → raw torque.
    scaled, fused = train_cli.build_env("jax:pendulum", "sac", cfg, 0)
    raw, _ = train_cli.build_env(
        "jax:pendulum", "sac", cfg, 0, scale_actions=False
    )
    assert fused
    s1, _ = scaled.reset(jax.random.key(0))
    s2, _ = raw.reset(jax.random.key(0))
    o1 = scaled.step(s1, jnp.asarray([0.5], jnp.float32))  # torque 1.0
    o2 = raw.step(s2, jnp.asarray([1.0], jnp.float32))     # torque 1.0
    np.testing.assert_allclose(np.asarray(o1.obs), np.asarray(o2.obs), rtol=1e-6)


def test_check_env_convention_sidecar(tmp_path):
    """Fused-path action-convention guard: first run records the flag in
    a ckpt-dir sidecar; a resume with a flipped flag warns; matched and
    legacy (no sidecar) resumes stay silent."""
    import warnings

    import train as train_cli

    d = str(tmp_path / "ck")
    train_cli.check_env_convention(d, "jax:pendulum", None, resume=False)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        train_cli.check_env_convention(d, "jax:pendulum", None, resume=True)
        # None and explicit True are the SAME effective convention on
        # pendulum (the env scales by default) — neither may warn.
        train_cli.check_env_convention(d, "jax:pendulum", True, resume=True)
    assert not caught
    with pytest.warns(UserWarning, match="other action convention"):
        train_cli.check_env_convention(d, "jax:pendulum", False, resume=True)
    # A fresh (non-resume) run into the same dir overwrites the stale
    # sidecar, so its own resumes are checked against ITS convention.
    train_cli.check_env_convention(d, "jax:pendulum", False, resume=False)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        train_cli.check_env_convention(d, "jax:pendulum", False, resume=True)
    assert not caught
    with pytest.warns(UserWarning, match="other action convention"):
        train_cli.check_env_convention(d, "jax:pendulum", None, resume=True)
    # Legacy dir without a sidecar: resume is silent (tolerant).
    legacy = str(tmp_path / "legacy")
    import os

    os.makedirs(legacy)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        train_cli.check_env_convention(legacy, "jax:pendulum", True, resume=True)
    assert not caught
    # No ckpt dir at all: no-op.
    train_cli.check_env_convention(None, "jax:pendulum", True, resume=True)


def test_check_env_convention_env_kwargs(tmp_path):
    """The sidecar also guards env-constructor kwargs: a resume that
    changes the env's difficulty knobs warns; matched kwargs and legacy
    (pre-env-kwargs) sidecars stay silent; --env-set scale_actions on
    pendulum counts as the real convention."""
    import warnings

    import train as train_cli

    d = str(tmp_path / "ck")
    kw = {"opp_skill": 0.5, "frame_skip": 4, "size": 36}
    train_cli.check_env_convention(d, "jax:pong", None, False, env_kwargs=kw)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        train_cli.check_env_convention(d, "jax:pong", None, True, env_kwargs=kw)
    assert not caught
    with pytest.warns(UserWarning, match="different environment"):
        train_cli.check_env_convention(
            d, "jax:pong", None, True, env_kwargs={**kw, "opp_skill": 1.0}
        )
    with pytest.warns(UserWarning, match="different environment"):
        train_cli.check_env_convention(d, "jax:pong", None, True, env_kwargs={})
    # Legacy sidecar without the env_kwargs key: tolerant.
    import json as json_mod
    import os

    legacy = str(tmp_path / "legacy")
    os.makedirs(legacy)
    with open(os.path.join(legacy, "env_convention.json"), "w") as f:
        json_mod.dump({"env": "jax:pong", "scale_actions": None}, f)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        train_cli.check_env_convention(legacy, "jax:pong", None, True, env_kwargs=kw)
    assert not caught
    # --env-set scale_actions=false on pendulum IS the effective
    # convention when no CLI flag is given (mirrors build_env).
    d2 = str(tmp_path / "pend")
    train_cli.check_env_convention(
        d2, "jax:pendulum", None, False, env_kwargs={"scale_actions": False}
    )
    with pytest.warns(UserWarning, match="other action convention"):
        train_cli.check_env_convention(d2, "jax:pendulum", None, True)
    # ...and spelling the SAME convention via the CLI flag instead of
    # --env-set must stay silent (scale_actions is excluded from the
    # kwargs comparison).
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        train_cli.check_env_convention(d2, "jax:pendulum", False, True)
    assert not caught
    # Resuming into a different ENV warns even with matching kwargs.
    with pytest.warns(UserWarning, match="different environment|belongs to"):
        train_cli.check_env_convention(d2, "jax:cartpole", None, True)
    # Host runs: the scale flip is host_loop's checkpoint-metric guard's
    # job — the sidecar must NOT double-warn it (env/kwargs only).
    d3 = str(tmp_path / "host")
    train_cli.check_env_convention(d3, "host:Pendulum-v1", True, False)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        train_cli.check_env_convention(d3, "host:Pendulum-v1", None, True)
    assert not caught


def test_build_env_mixture_spec():
    """'mixture:<members>' builds the heterogeneous fleet env (ISSUE
    11): per-type weights parse from the spec, --env-set reaches the
    mixture maker, and bad members/kwargs exit with the friendly
    message."""
    import train as train_cli
    from actor_critic_tpu.envs.mixture import MixtureEnv

    cfg = PRESETS["a2c_cartpole"].config
    env, fused = train_cli.build_env(
        "mixture:cartpole*2,pendulum,acrobot", "a2c", cfg, 0,
        env_kwargs={"randomize": 0.2, "action_bins": 7},
    )
    assert fused and isinstance(env, MixtureEnv)
    assert env.member_names == ("cartpole", "pendulum", "acrobot")
    assert env.init_weights == (2.0, 1.0, 1.0)
    assert env.spec.action_dim == 7  # action_bins reached the maker
    with pytest.raises(SystemExit, match="bad mixture env"):
        train_cli.build_env("mixture:cartpole,frogger", "a2c", cfg, 0)
    with pytest.raises(SystemExit, match="bad --env-set"):
        train_cli.build_env(
            "mixture:cartpole,maze", "a2c", cfg, 0, env_kwargs={"nope": 1}
        )


def test_mixture_preset_resolves():
    pre = resolve("a2c_mixture", None, None, {})
    assert pre.env.startswith("mixture:")
    assert pre.env_kwargs == {"randomize": 0.2}


@pytest.mark.slow
def test_cli_data_plane_device_end_to_end(tmp_path):
    """train.py runs a tiny async PPO job through the device data plane
    (--data-plane device --data-plane-codec int8) end to end, and the
    summary line carries real learner metrics. Marked slow (a full
    train.py subprocess is ~10 s of mostly jax import): tier-1 covers
    the same driver path in-process (test_data_plane ckpt e2e,
    test_async_host device tests) and the flag plumbing via
    test_data_plane_flag_validation."""
    metrics = tmp_path / "m.jsonl"
    cmd = [
        sys.executable, "train.py",
        "--algo", "ppo", "--env", "host:CartPole-v1",
        "--iterations", "3", "--log-every", "1", "--quiet",
        "--set", "num_envs=4", "--set", "rollout_steps=8",
        "--set", "epochs=1", "--set", "num_minibatches=1",
        "--set", "hidden=16",
        "--async-actors", "2", "--data-plane", "device",
        "--data-plane-codec", "int8",
        "--metrics", str(metrics),
    ]
    env = {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
           "PATH": "/usr/bin:/bin"}
    import os

    env.update({k: v for k, v in os.environ.items() if k not in env})
    r = subprocess.run(
        cmd, capture_output=True, text=True, env=env, cwd="/root/repo"
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rows = [json.loads(line) for line in metrics.read_text().splitlines()]
    assert rows[-1]["iter"] == 3
    assert "consumed_env_steps" in rows[-1]
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["loss"] is not None


def test_data_plane_flag_validation():
    """--data-plane device exits early (before any env/device work) on
    every doomed combination: no actor services to relocate, and the
    multi-host learner (host-array global batches) — ISSUE 13."""
    import train as train_cli

    base = ["--iterations", "1", "--quiet"]
    with pytest.raises(SystemExit, match="async-actors"):
        train_cli.main(
            ["--algo", "ppo", "--env", "host:CartPole-v1",
             "--data-plane", "device"] + base
        )
    with pytest.raises(SystemExit, match="single-host"):
        train_cli.main(
            ["--algo", "ppo", "--env", "host:CartPole-v1",
             "--data-plane", "device", "--async-actors", "2",
             "--distributed", "--gossip", "--mailbox-dir", "/tmp/mb"]
            + base
        )
    with pytest.raises(SystemExit):
        # argparse rejects unknown plane codecs at parse time.
        train_cli.main(
            ["--algo", "ppo", "--env", "host:CartPole-v1",
             "--data-plane-codec", "bf16"] + base
        )


def test_curriculum_flag_validation():
    """--curriculum exits early (before any env/device work) on every
    doomed combination: non-mixture env, no eval cadence, bad spec."""
    import train as train_cli

    base = ["--iterations", "1", "--quiet"]
    with pytest.raises(SystemExit, match="mixture"):
        train_cli.main(
            ["--algo", "a2c", "--env", "jax:cartpole",
             "--curriculum", "10:1"] + base
        )
    with pytest.raises(SystemExit, match="eval-every"):
        train_cli.main(
            ["--algo", "a2c", "--env", "mixture:cartpole,maze",
             "--curriculum", "10:1,2"] + base
        )
    with pytest.raises(SystemExit, match="bad --curriculum"):
        train_cli.main(
            ["--algo", "a2c", "--env", "mixture:cartpole,maze",
             "--curriculum", "10:1,2,3", "--eval-every", "1"] + base
        )
    with pytest.raises(SystemExit, match="bad --curriculum"):
        train_cli.main(
            ["--algo", "a2c", "--env", "mixture:cartpole,maze",
             "--curriculum", "garbage", "--eval-every", "1"] + base
        )
