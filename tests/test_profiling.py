"""Profiler harness tests: trace produces artifacts, time_fn fences
correctly, nan_guard fires exactly on non-finite input."""

import logging
import os

import jax
import jax.numpy as jnp

from actor_critic_tpu.utils import profiling


def test_time_fn_returns_positive_time():
    f = jax.jit(lambda x: x @ x)
    x = jnp.ones((128, 128))
    dt = profiling.time_fn(f, x, iters=3, warmup=1)
    assert dt > 0


def test_trace_writes_artifacts(tmp_path):
    logdir = str(tmp_path / "prof")
    f = jax.jit(lambda x: (x * 2).sum())
    with profiling.trace(logdir):
        jax.block_until_ready(f(jnp.ones((64, 64))))
    files = [os.path.join(r, f_) for r, _, fs in os.walk(logdir) for f_ in fs]
    assert files, "profiler trace produced no files"


def test_nan_guard_warns_only_on_nonfinite(caplog):
    @jax.jit
    def step(x):
        profiling.nan_guard({"loss": x}, name="test-metrics")
        return x + 1

    with caplog.at_level(logging.WARNING):
        jax.block_until_ready(step(jnp.ones(4)))
        jax.effects_barrier()
    assert "non-finite" not in caplog.text

    with caplog.at_level(logging.WARNING):
        jax.block_until_ready(step(jnp.array([1.0, jnp.nan, 3.0, 4.0])))
        jax.effects_barrier()
    assert "non-finite" in caplog.text
