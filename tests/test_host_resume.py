"""Host-path checkpoint/resume + greedy eval (VERDICT.md round-1 items
5-6; SURVEY.md §5.3-5.4 extended to the host trainers).

Resume contract for host envs: the DEVICE side (params/opt/learner/PRNG/
env-step counter) and the pool's normalizer stats restore exactly; the
env simulator state does not (gymnasium can't serialize it), so resumed
pools restart fresh episodes. The tests therefore assert exact equality
of the restored device state and normalizer stats, not of trajectories.
"""

import jax
import numpy as np
import pytest

from actor_critic_tpu.algos import ddpg, ppo
from actor_critic_tpu.algos.host_loop import should_log
from actor_critic_tpu.envs.host_pool import HostEnvPool
from actor_critic_tpu.utils.checkpoint import Checkpointer


def _tiny_ppo_cfg():
    return ppo.PPOConfig(
        num_envs=2, rollout_steps=8, epochs=1, num_minibatches=1, hidden=(16,)
    )


def _tiny_ddpg_cfg():
    return ddpg.DDPGConfig(
        num_envs=2, steps_per_iter=4, updates_per_iter=1, buffer_capacity=512,
        batch_size=8, warmup_steps=8, hidden=(16,),
    )


def _trees_equal(a, b):
    import jax.numpy as jnp

    def raw(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            return np.asarray(jax.random.key_data(x))
        return np.asarray(x)

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(raw(x), raw(y))


def test_should_log_first_iteration():
    # Log-from-iteration-1: a long run must produce a metrics row after
    # ONE iteration regardless of cadence (round-1 left an empty file).
    assert should_log(1, 10, 100)
    assert should_log(1, 0, 100)
    assert not should_log(2, 10, 100)
    assert should_log(100, 10, 100)


def test_ppo_host_resume_restores_exact_state(tmp_path):
    cfg = _tiny_ppo_cfg()
    pool = HostEnvPool("CartPole-v1", num_envs=2, seed=0)
    with Checkpointer(tmp_path / "ck") as ck:
        params1, opt1, _ = ppo.train_host(
            pool, cfg, num_iterations=3, seed=0, log_every=0,
            ckpt=ck, save_every=2,
        )
        ck.wait()
        saved_rms_count = pool.obs_rms.count
        assert ck.latest_step() == 3
    pool.close()

    # "New process": fresh pool, resume finds the run complete at 3 and
    # returns the restored state without running further iterations.
    pool2 = HostEnvPool("CartPole-v1", num_envs=2, seed=0)
    with Checkpointer(tmp_path / "ck") as ck:
        params2, opt2, history = ppo.train_host(
            pool2, cfg, num_iterations=3, seed=0, log_every=0,
            ckpt=ck, resume=True,
        )
    _trees_equal(params1, params2)
    _trees_equal(opt1, opt2)
    assert history == []
    # Normalizer stats came back through pool.set_state (+1 reset batch).
    assert pool2.obs_rms.count == pytest.approx(saved_rms_count, rel=0.2)
    pool2.close()


def test_ppo_host_resume_continues_training(tmp_path):
    cfg = _tiny_ppo_cfg()
    pool = HostEnvPool("CartPole-v1", num_envs=2, seed=0)
    with Checkpointer(tmp_path / "ck") as ck:
        ppo.train_host(
            pool, cfg, num_iterations=2, seed=0, log_every=0,
            ckpt=ck, save_every=1,
        )
        ck.wait()
    pool.close()

    pool2 = HostEnvPool("CartPole-v1", num_envs=2, seed=0)
    with Checkpointer(tmp_path / "ck") as ck:
        _, _, history = ppo.train_host(
            pool2, cfg, num_iterations=4, seed=0, log_every=1,
            ckpt=ck, save_every=1, resume=True,
        )
        assert ck.latest_step() == 4
    # Only iterations 3..4 ran (history rows are 1-based iteration ids).
    assert [it for it, _ in history] == [3, 4]
    pool2.close()


def test_offpolicy_host_resume_restores_learner(tmp_path):
    cfg = _tiny_ddpg_cfg()
    pool = HostEnvPool(
        "Pendulum-v1", num_envs=2, seed=0, normalize_reward=False
    )
    with Checkpointer(tmp_path / "ck") as ck:
        learner1, _ = ddpg.train_host(
            pool, cfg, num_iterations=3, seed=0, log_every=0,
            ckpt=ck, save_every=2,
        )
        ck.wait()
    pool.close()

    pool2 = HostEnvPool(
        "Pendulum-v1", num_envs=2, seed=0, normalize_reward=False
    )
    with Checkpointer(tmp_path / "ck") as ck:
        learner2, history = ddpg.train_host(
            pool2, cfg, num_iterations=3, seed=0, log_every=0,
            ckpt=ck, resume=True,
        )
    _trees_equal(learner1, learner2)  # params, targets, opt, replay ring
    assert history == []
    pool2.close()


def test_offpolicy_replay_free_checkpoint(tmp_path):
    """save_replay=False: the checkpoint excludes the ring (orders of
    magnitude smaller on disk), resume restores params/opt/key exactly,
    warns about the fresh-buffer semantics, reattaches a zeroed
    full-capacity ring, and training continues (updates gated until the
    ring refills past one batch)."""
    cfg = _tiny_ddpg_cfg()

    pool = HostEnvPool(
        "Pendulum-v1", num_envs=2, seed=0,
        normalize_obs=False, normalize_reward=False,
    )
    with Checkpointer(tmp_path / "slim") as ck:
        learner1, _ = ddpg.train_host(
            pool, cfg, num_iterations=3, seed=0, log_every=0,
            ckpt=ck, save_every=3, save_replay=False,
        )
        ck.wait()
    pool.close()

    # Disk sizes are NOT asserted: at toy scale orbax's compression of a
    # mostly-zero 512-slot ring lands within filesystem/layout noise of
    # the stub (observed flaking by a few hundred bytes either way).
    # The structural check below is the real guarantee — the SAVED tree
    # carries a one-slot stub, so a Humanoid-scale ring (~3 GB) can
    # never enter the checkpoint.
    from actor_critic_tpu.algos.host_loop import host_ckpt_state

    pool = HostEnvPool(
        "Pendulum-v1", num_envs=2, seed=0,
        normalize_obs=False, normalize_reward=False,
    )
    saved_tree = host_ckpt_state(pool, save_replay=False, learner=learner1)
    pool.close()
    stub_leaves = jax.tree.leaves(saved_tree["learner"].replay.storage)
    assert all(leaf.shape[0] == 1 for leaf in stub_leaves)
    assert replay_capacity(learner1) == cfg.buffer_capacity  # untouched

    # Resume with no extra iterations: exact param restore + the ring
    # comes back EMPTY at full capacity (not the saved stub).
    pool = HostEnvPool(
        "Pendulum-v1", num_envs=2, seed=0,
        normalize_obs=False, normalize_reward=False,
    )
    with Checkpointer(tmp_path / "slim") as ck:
        with pytest.warns(UserWarning, match="replay-free"):
            learner2, history = ddpg.train_host(
                pool, cfg, num_iterations=3, seed=0, log_every=0,
                ckpt=ck, resume=True, save_replay=False,
            )
    pool.close()
    assert history == []
    _trees_equal(learner1.actor_params, learner2.actor_params)
    assert int(learner2.replay.size) == 0
    assert replay_capacity(learner2) == cfg.buffer_capacity

    # Resume WITH extra iterations: training continues, refilling the
    # fresh ring (2 iterations x steps_per_iter x num_envs inserts).
    pool = HostEnvPool(
        "Pendulum-v1", num_envs=2, seed=0,
        normalize_obs=False, normalize_reward=False,
    )
    with Checkpointer(tmp_path / "slim") as ck:
        with pytest.warns(UserWarning, match="replay-free"):
            learner3, history = ddpg.train_host(
                pool, cfg, num_iterations=5, seed=0, log_every=1,
                ckpt=ck, resume=True, save_replay=False,
            )
    pool.close()
    assert [it for it, _ in history] == [4, 5]
    assert int(learner3.replay.size) == 2 * cfg.steps_per_iter * 2
    assert replay_capacity(learner3) == cfg.buffer_capacity


def replay_capacity(learner):
    import jax

    return jax.tree.leaves(learner.replay.storage)[0].shape[0]


def test_replay_free_checkpoint_keeps_quantizer_stats(tmp_path):
    """ISSUE 8: with quantized replay, save_replay=False still saves the
    running mean/scale stats (strip_replay truncates STORAGE only), and
    resume reattaches the fresh full-capacity ring while keeping the
    restored stats — fresh transitions must encode against the
    standardization the restored critic trained under, and a re-zeroed
    scale would decode early post-resume batches through a different
    affine map."""
    import dataclasses

    cfg = dataclasses.replace(_tiny_ddpg_cfg(), replay_dtype="mixed")

    pool = HostEnvPool(
        "Pendulum-v1", num_envs=2, seed=0,
        normalize_obs=False, normalize_reward=False,
    )
    with Checkpointer(tmp_path / "qslim") as ck:
        learner1, _ = ddpg.train_host(
            pool, cfg, num_iterations=3, seed=0, log_every=0,
            ckpt=ck, save_every=3, save_replay=False,
        )
        ck.wait()
    pool.close()
    # The run really quantized (int8 ring) and really learned stats.
    assert jax.tree.leaves(learner1.replay.storage)[0].dtype == np.int8
    assert int(learner1.replay.quant.obs.count) > 0

    # The SAVED tree: one-slot storage stub, stats intact.
    from actor_critic_tpu.algos.host_loop import host_ckpt_state

    pool = HostEnvPool(
        "Pendulum-v1", num_envs=2, seed=0,
        normalize_obs=False, normalize_reward=False,
    )
    saved_tree = host_ckpt_state(pool, save_replay=False, learner=learner1)
    stub = saved_tree["learner"].replay
    assert all(leaf.shape[0] == 1 for leaf in jax.tree.leaves(stub.storage))
    _trees_equal(stub.quant, learner1.replay.quant)
    pool.close()

    # Resume: empty full-capacity ring, EXACT stats back.
    pool = HostEnvPool(
        "Pendulum-v1", num_envs=2, seed=0,
        normalize_obs=False, normalize_reward=False,
    )
    with Checkpointer(tmp_path / "qslim") as ck:
        with pytest.warns(UserWarning, match="replay-free"):
            learner2, history = ddpg.train_host(
                pool, cfg, num_iterations=3, seed=0, log_every=0,
                ckpt=ck, resume=True, save_replay=False,
            )
    pool.close()
    assert history == []
    assert int(learner2.replay.size) == 0
    assert replay_capacity(learner2) == cfg.buffer_capacity
    _trees_equal(learner2.replay.quant, learner1.replay.quant)


@pytest.mark.parametrize("trained_normalized", [True, False],
                         ids=["norm-ckpt-raw-pool", "raw-ckpt-norm-pool"])
def test_resume_warns_on_normalization_mismatch(tmp_path, trained_normalized):
    """host_resume warns in BOTH mismatch directions: a checkpoint whose
    obs-normalizer accumulated real statistics resumed into a raw-obs
    pool, and a raw-obs checkpoint resumed into a normalizing pool — the
    restored networks would silently act off-distribution either way."""
    cfg = _tiny_ppo_cfg()
    pool = HostEnvPool(
        "CartPole-v1", num_envs=2, seed=0,
        normalize_obs=trained_normalized, normalize_reward=False,
    )
    with Checkpointer(tmp_path / "ck") as ck:
        ppo.train_host(
            pool, cfg, num_iterations=2, seed=0, log_every=0,
            ckpt=ck, save_every=1,
        )
        ck.wait()
    pool.close()

    mismatched = HostEnvPool(
        "CartPole-v1", num_envs=2, seed=0,
        normalize_obs=not trained_normalized, normalize_reward=False,
    )
    with Checkpointer(tmp_path / "ck") as ck:
        with pytest.warns(UserWarning, match="off-distribution"):
            ppo.train_host(
                mismatched, cfg, num_iterations=2, seed=0, log_every=0,
                ckpt=ck, resume=True,
            )
    mismatched.close()

    # Matched resume stays silent (on THIS warning; unrelated library
    # warnings must not fail the assertion).
    matched = HostEnvPool(
        "CartPole-v1", num_envs=2, seed=0,
        normalize_obs=trained_normalized, normalize_reward=False,
    )
    import warnings as _warnings

    with Checkpointer(tmp_path / "ck") as ck:
        with _warnings.catch_warnings(record=True) as caught:
            _warnings.simplefilter("always")
            ppo.train_host(
                matched, cfg, num_iterations=2, seed=0, log_every=0,
                ckpt=ck, resume=True,
            )
    assert not [w for w in caught if "off-distribution" in str(w.message)]
    matched.close()


def test_ppo_host_eval_rides_log_row():
    cfg = _tiny_ppo_cfg()
    pool = HostEnvPool("CartPole-v1", num_envs=2, seed=0)
    _, _, history = ppo.train_host(
        pool, cfg, num_iterations=2, seed=0, log_every=0,
        eval_every=2, eval_envs=2, eval_steps=64,
    )
    rows = dict(history)
    assert 2 in rows and "eval_return" in rows[2]
    assert np.isfinite(rows[2]["eval_return"])
    assert "env_steps" in rows[2]
    pool.close()


def test_resume_warns_on_action_convention_mismatch(tmp_path):
    """The scale_actions convention rides the checkpoint's metrics JSON
    (not the state tree — that would break old checkpoints); resuming
    under the other convention must warn."""
    from actor_critic_tpu.algos import ddpg

    cfg = _tiny_ddpg_cfg()
    pool = HostEnvPool(
        "Pendulum-v1", num_envs=2, seed=0, normalize_obs=False,
        normalize_reward=False, scale_actions=True,
    )
    with Checkpointer(tmp_path / "ck") as ck:
        ddpg.train_host(
            pool, cfg, num_iterations=2, seed=0, log_every=0,
            ckpt=ck, save_every=1,
        )
        ck.wait()
    pool.close()

    clipped = HostEnvPool(
        "Pendulum-v1", num_envs=2, seed=0, normalize_obs=False,
        normalize_reward=False, scale_actions=False,
    )
    with Checkpointer(tmp_path / "ck") as ck:
        with pytest.warns(UserWarning, match="action convention|execute differently"):
            ddpg.train_host(
                clipped, cfg, num_iterations=2, seed=0, log_every=0,
                ckpt=ck, resume=True,
            )
    clipped.close()
