"""Native C++ env engine vs gymnasium: exact dynamics parity, SAME_STEP
auto-reset semantics, and HostEnvPool integration."""

import numpy as np
import pytest

gym = pytest.importorskip("gymnasium")

from actor_critic_tpu.envs.host_pool import HostEnvPool
from actor_critic_tpu.envs.native_pool import NativeVecEnv


def test_cartpole_dynamics_match_gymnasium():
    """From identical injected states, N steps of the native engine must
    reproduce gymnasium's CartPole-v1 trajectory bitwise-closely."""
    genv = gym.make("CartPole-v1").unwrapped
    genv.reset(seed=0)
    nenv = NativeVecEnv("CartPole-v1", num_envs=1)
    nenv.reset(seed=0)

    rng = np.random.default_rng(42)
    start = rng.uniform(-0.05, 0.05, size=4).astype(np.float32)
    genv.state = np.asarray(start, np.float64)
    nenv.set_state(start[None, :])

    for t in range(60):
        a = int(rng.integers(0, 2))
        gobs, grew, gterm, gtrunc, _ = genv.step(a)
        nobs, nrew, nterm, ntrunc, ninfo = nenv.step(np.array([a]))
        if gterm:
            # native autoresets; compare the pre-reset obs
            np.testing.assert_allclose(
                ninfo["final_obs"][0], gobs.astype(np.float32), rtol=1e-5, atol=1e-6
            )
            assert bool(nterm[0])
            break
        np.testing.assert_allclose(nobs[0], gobs.astype(np.float32), rtol=1e-5, atol=1e-6)
        assert nrew[0] == grew
        assert not bool(nterm[0])


def test_pendulum_dynamics_match_gymnasium():
    genv = gym.make("Pendulum-v1").unwrapped
    genv.reset(seed=0)
    nenv = NativeVecEnv("Pendulum-v1", num_envs=1)
    nenv.reset(seed=0)

    rng = np.random.default_rng(1)
    start = np.array([rng.uniform(-np.pi, np.pi), rng.uniform(-1, 1)], np.float32)
    genv.state = np.asarray(start, np.float64)
    nenv.set_state(start[None, :])

    for t in range(50):
        a = rng.uniform(-2, 2, size=1).astype(np.float32)
        gobs, grew, _, _, _ = genv.step(a)
        nobs, nrew, nterm, ntrunc, _ = nenv.step(a[None, :])
        np.testing.assert_allclose(nobs[0], gobs.astype(np.float32), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(nrew[0], grew, rtol=1e-4, atol=1e-5)
        assert not bool(nterm[0])


def test_autoreset_same_step_semantics():
    """Termination: final_obs carries the ending obs, obs the new episode,
    and step counters restart (time-limit truncation at 500)."""
    nenv = NativeVecEnv("CartPole-v1", num_envs=4)
    obs, _ = nenv.reset(seed=7)
    assert obs.shape == (4, 4)
    done_seen = False
    for t in range(600):
        acts = np.ones(4, np.int64)  # constant push → quick termination
        obs, rew, term, trunc, info = nenv.step(acts)
        assert obs.shape == (4, 4) and rew.shape == (4,)
        if (term | trunc).any():
            done_seen = True
            i = int(np.argmax(term | trunc))
            # SAME_STEP contract: final_obs[i] is the PRE-reset terminal
            # observation. For a true termination that state must violate
            # the CartPole bounds (|x| > 2.4 or |theta| > 12°) — a reset
            # state (uniform [-0.05, 0.05]) can never satisfy this, so the
            # assertion genuinely distinguishes the two.
            fo = np.asarray(info["final_obs"][i], np.float64)
            if term[i]:
                assert abs(fo[0]) > 2.4 or abs(fo[2]) > 12 * np.pi / 180
            # reset obs is near the origin (fresh uniform [-0.05, 0.05])
            assert np.all(np.abs(obs[i]) <= 0.05 + 1e-6)
        if done_seen and t > 20:
            break
    assert done_seen


def test_hostenvpool_native_backend():
    pool = HostEnvPool(
        "CartPole-v1", num_envs=8, backend="native",
        normalize_obs=True, normalize_reward=False,
    )
    obs = pool.reset()
    assert obs.shape == (8, 4)
    for _ in range(10):
        out = pool.step(np.zeros(8, np.int64))
    assert out.obs.shape == (8, 4)
    assert out.raw_reward.shape == (8,)
    assert pool.spec.discrete and pool.spec.action_dim == 2


def test_native_faster_than_gym():
    """The point of the native engine: batch stepping beats the Python
    per-env loop (sanity margin only — CI noise tolerant)."""
    import time

    E, T = 64, 200
    native = HostEnvPool("CartPole-v1", E, backend="native",
                         normalize_obs=False, normalize_reward=False)
    gympool = HostEnvPool("CartPole-v1", E, backend="gym",
                          normalize_obs=False, normalize_reward=False)
    acts = np.zeros(E, np.int64)
    for pool in (native, gympool):
        pool.reset()
        pool.step(acts)  # warm
    t0 = time.perf_counter(); [native.step(acts) for _ in range(T)]
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter(); [gympool.step(acts) for _ in range(T)]
    t_gym = time.perf_counter() - t0
    assert t_native < t_gym, (t_native, t_gym)


@pytest.mark.parametrize("seed", [5, 11, 23, 47])
def test_mountaincar_dynamics_match_gymnasium(seed):
    """MountainCarContinuous-v0: clipped force, inelastic left wall, raw-
    action reward penalty, +100 goal bonus — stepped against gymnasium
    from identical injected states. Multiple seeds because the env's
    float32 per-op arithmetic (emulated in C) diverges chaotically if
    even one op rounds differently — a single lucky seed can't certify
    it."""
    genv = gym.make("MountainCarContinuous-v0").unwrapped
    genv.reset(seed=0)
    nenv = NativeVecEnv("MountainCarContinuous-v0", num_envs=1)
    nenv.reset(seed=0)

    rng = np.random.default_rng(seed)
    # float32 start state: gymnasium's MountainCar state IS float32, so
    # injecting float64 would give its first step different (float64)
    # per-op arithmetic than every later step.
    start32 = np.array([rng.uniform(-0.6, -0.4), 0.0], np.float32)
    genv.state = start32.copy()
    nenv.set_state(start32.astype(np.float64)[None, :])

    # Full-episode horizon: gymnasium rounds MountainCar state to float32
    # each step (unlike its other classic-control envs); the native
    # engine mirrors that, and without the mirroring the wall/clip
    # discontinuities amplify the rounding difference chaotically
    # (~0.55 obs divergence by step 999) — so the long horizon is the
    # assertion that matters.
    for t in range(990):  # just under the 999 limit (unwrapped gym never
        # truncates; the native engine would auto-reset at 999)
        # Out-of-range actions exercise the clip-for-force /
        # raw-for-penalty asymmetry.
        a = np.array([rng.uniform(-1.5, 1.5)], np.float32)
        gobs, grew, gterm, gtrunc, _ = genv.step(a)
        nobs, nrew, nterm, ntrunc, ninfo = nenv.step(a[None, :])
        if gterm:
            np.testing.assert_allclose(
                ninfo["final_obs"][0], gobs.astype(np.float32),
                rtol=1e-5, atol=1e-6,
            )
            assert bool(nterm[0])
            break
        np.testing.assert_allclose(
            nobs[0], gobs.astype(np.float32), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(nrew[0], grew, rtol=1e-5, atol=1e-6)
        assert not bool(nterm[0])


def test_acrobot_dynamics_match_gymnasium():
    """Acrobot-v1: RK4 book dynamics, angle wrap, velocity bounds — the
    native trajectory must track gymnasium's step for step."""
    genv = gym.make("Acrobot-v1").unwrapped
    genv.reset(seed=0)
    nenv = NativeVecEnv("Acrobot-v1", num_envs=1)
    nenv.reset(seed=0)

    rng = np.random.default_rng(9)
    start = rng.uniform(-0.1, 0.1, size=4)
    genv.state = start.astype(np.float64)
    nenv.set_state(start[None, :])

    for t in range(120):
        a = int(rng.integers(0, 3))
        gobs, grew, gterm, gtrunc, _ = genv.step(a)
        nobs, nrew, nterm, ntrunc, ninfo = nenv.step(np.array([a]))
        if gterm:
            np.testing.assert_allclose(
                ninfo["final_obs"][0], gobs.astype(np.float32),
                rtol=1e-4, atol=1e-5,
            )
            assert bool(nterm[0])
            break
        np.testing.assert_allclose(
            nobs[0], gobs.astype(np.float32), rtol=1e-4, atol=1e-5
        )
        assert nrew[0] == grew
        assert not bool(nterm[0])


def test_new_native_envs_under_hostenvpool():
    """Both new envs ride HostEnvPool's native backend end-to-end."""
    for env_id, disc in (
        ("MountainCarContinuous-v0", False), ("Acrobot-v1", True),
    ):
        pool = HostEnvPool(
            env_id, num_envs=4, seed=3, backend="native",
            normalize_obs=False, normalize_reward=False,
        )
        obs = pool.reset()
        assert obs.shape == (4, pool.spec.obs_shape[0])
        if disc:
            acts = np.zeros(4, np.int64)
        else:
            acts = np.zeros((4, 1), np.float32)
        out = pool.step(acts)
        assert np.isfinite(out.obs).all()
        pool.close()


def test_mountaincar_goal_termination_and_bonus():
    """The +100 goal bonus, raw-action penalty, and termination flag —
    injected near-goal state so the terminal branch actually runs."""
    genv = gym.make("MountainCarContinuous-v0").unwrapped
    genv.reset(seed=0)
    nenv = NativeVecEnv("MountainCarContinuous-v0", num_envs=1)
    nenv.reset(seed=0)

    start32 = np.array([0.445, 0.055], np.float32)
    genv.state = start32.copy()
    nenv.set_state(start32.astype(np.float64)[None, :])

    a = np.array([1.0], np.float32)
    gobs, grew, gterm, _, _ = genv.step(a)
    nobs, nrew, nterm, _, ninfo = nenv.step(a[None, :])
    assert gterm, "test setup must reach the goal in one step"
    assert bool(nterm[0])
    np.testing.assert_allclose(nrew[0], grew, rtol=1e-6)  # ≈ 100 - 0.1
    assert nrew[0] > 99.0
    np.testing.assert_allclose(
        ninfo["final_obs"][0], gobs.astype(np.float32), rtol=1e-5, atol=1e-6
    )
    # SAME_STEP: obs holds the fresh episode (position ∈ [-0.6, -0.4]).
    assert -0.6 <= nobs[0, 0] <= -0.4 and nobs[0, 1] == 0.0


def test_acrobot_termination_parity():
    """Terminal condition (-cosθ1 - cos(θ1+θ2) > 1) and 0-vs-(-1) reward,
    from an injected state one step short of the goal height."""
    genv = gym.make("Acrobot-v1").unwrapped
    genv.reset(seed=0)
    nenv = NativeVecEnv("Acrobot-v1", num_envs=1)
    nenv.reset(seed=0)

    start = np.array([2.8, 0.0, 0.0, 0.0], np.float64)  # near-vertical link 1
    genv.state = start.copy()
    nenv.set_state(start[None, :])

    a = 1  # zero torque
    gobs, grew, gterm, _, _ = genv.step(a)
    nobs, nrew, nterm, _, ninfo = nenv.step(np.array([a]))
    assert gterm, "test setup must terminate in one step"
    assert bool(nterm[0])
    assert nrew[0] == grew == 0.0
    np.testing.assert_allclose(
        ninfo["final_obs"][0], gobs.astype(np.float32), rtol=1e-5, atol=1e-6
    )
    # fresh episode obs: all four state vars uniform in [-0.1, 0.1]
    assert abs(nobs[0, 4]) <= 0.1 and abs(nobs[0, 5]) <= 0.1


@pytest.mark.slow
def test_ppo_learns_native_acrobot():
    """Learning test on the C++ engine's Acrobot: the full host PPO path
    (native batch stepping + normalization + jitted learner) reaches
    greedy eval >= -100 (the conventional solve bar) within 150
    iterations / 307k env steps. The recorded run
    (results/ppo_acrobot_native_cpu.jsonl) hits -83.8 by iteration 25,
    so 150 leaves wide margin; wall-clock is ~10 s of stepping on the
    1-core host."""
    from actor_critic_tpu.algos import ppo

    pool = HostEnvPool(
        "Acrobot-v1", num_envs=16, seed=0, backend="native",
        normalize_obs=True, normalize_reward=True,
    )
    cfg = ppo.PPOConfig(
        num_envs=16, rollout_steps=128, epochs=4, num_minibatches=8,
        anneal_iters=300, lr_final=0.0,  # the recorded run's schedule —
        # this test replays its first 150 iterations exactly
    )
    best = -float("inf")
    _, _, history = ppo.train_host(
        pool, cfg, num_iterations=150, seed=0, log_every=0,
        eval_every=50, eval_envs=8, eval_steps=500,
    )
    for _, m in history:
        if "eval_return" in m:
            best = max(best, m["eval_return"])
    pool.close()
    assert best >= -100.0, f"native Acrobot not learned: best eval {best}"
