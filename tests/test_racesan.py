"""Tier-1 wiring for the deterministic race sanitizer (ISSUE 7).

Four layers:

1. **Scheduler mechanics** — a seeded schedule replays bit-identically
   (trace AND outcome), different seeds genuinely permute, and a
   deliberately racy toy class is caught within N schedules — then
   reproduced from its seed.
2. **Poisoner tripwires** — write-after-publish freezing crashes an
   in-place producer mutation at the write site; the scribble turns a
   stale consumer alias into deterministic garbage.
3. **The two PR 6 bugs as runtime regressions** — the reverted
   copy-on-transfer consumer (`consumer="alias"`) is detected on every
   schedule under the poisoner, and the hardened
   `PolicyPublisher.publish` makes actor-side views unwritable.
4. **The fast profile** — the fixed-seed ~100-schedule sweep tier-1
   runs (scripts/tier1.sh invokes the same profile via
   scripts/racesan.py) comes back clean on the real queue/publisher.

The queue/publisher layers run on plain numpy + threads (no jax
import, no device); the param-mailbox layer (ISSUE 9) imports
`parallel.multihost`, which pulls jax transitively — import only,
still no device work.
"""

import numpy as np
import pytest

from actor_critic_tpu.algos.traj_queue import PolicyPublisher, TrajQueue
from actor_critic_tpu.analysis import racesan
from actor_critic_tpu.analysis.racesan import CoopScheduler, RacesanError

# ---------------------------------------------------------------------------
# scheduler mechanics
# ---------------------------------------------------------------------------


def test_seeded_schedule_replays_bit_identically():
    traces = []
    reports = []
    for _ in range(2):
        sched = CoopScheduler(seed=11)
        order = []

        def worker(name, sched=sched, order=order):
            for i in range(3):
                order.append((name, i))
                sched.yield_point(f"step-{i}")

        for n in ("a", "b", "c"):
            sched.spawn(n, lambda n=n: worker(n))
        trace = sched.run()
        traces.append(trace)
        reports.append(order)
    assert traces[0] == traces[1]
    assert reports[0] == reports[1]


def test_different_seeds_permute_interleavings():
    def trace_of(seed):
        sched = CoopScheduler(seed)

        def worker(sched=sched):
            for i in range(4):
                sched.yield_point(f"s{i}")

        for n in ("a", "b"):
            sched.spawn(n, worker)
        return tuple(sched.run())

    traces = {trace_of(s) for s in range(12)}
    assert len(traces) > 1, "12 seeds produced one interleaving"


class _RacyCounter:
    """read → yield → write: the textbook lost-update window."""

    def __init__(self):
        self.n = 0

    def incr(self, sched):
        v = self.n
        sched.yield_point("between-read-and-write")
        self.n = v + 1


def _lost_update(seed, incrs=3):
    sched = CoopScheduler(seed)
    counter = _RacyCounter()

    def worker(sched=sched):
        for _ in range(incrs):
            counter.incr(sched)

    for n in ("t0", "t1"):
        sched.spawn(n, worker)
    sched.run()
    return counter.n < 2 * incrs


def test_racy_toy_class_is_caught_within_n_schedules():
    hits = [s for s in range(20) if _lost_update(s)]
    assert hits, "no lost update surfaced in 20 seeded schedules"
    # the catching seed reproduces its race deterministically
    assert _lost_update(hits[0])
    assert _lost_update(hits[0])


def test_blocked_participant_trips_the_deadline_not_a_hang():
    import threading

    sched = CoopScheduler(seed=0)
    ev = threading.Event()  # never set: a real blocking wait

    def blocker():
        ev.wait()  # outside the scheduler: nobody can run

    sched.spawn("blocker", blocker)
    with pytest.raises(RacesanError, match="no progress"):
        sched.run(timeout_s=0.5)
    ev.set()  # let the daemon thread exit


# ---------------------------------------------------------------------------
# poisoner tripwires
# ---------------------------------------------------------------------------


def test_freeze_on_publish_crashes_producer_write_at_the_write_site():
    pub = PolicyPublisher({"w": np.zeros((2, 2), np.float32)})
    racesan.freeze_on_publish(pub)
    retained = {"w": np.ones((2, 2), np.float32)}
    pub.publish(retained, version=1)
    with pytest.raises(ValueError, match="read-only"):
        retained["w"][...] = 2.0  # the write site, not a later read


def test_queue_poisoner_freezes_leases_and_scribbles_releases():
    q = TrajQueue(depth=2, register_gauge=False)
    racesan.attach_queue_poisoner(q)
    q.put({"x": np.full((3,), 5.0, np.float32)}, version=0)
    block = q.get(timeout=0)
    with pytest.raises(ValueError, match="read-only"):
        block.arrays["x"][0] = 1.0  # writing a leased slot crashes
    stale = np.asarray(block.arrays["x"])  # zero-copy alias kept...
    q.release(block)
    # ...reads the quarantine sentinel deterministically after release
    assert float(stale[0]) == float(np.finfo(np.float32).min)
    q.close()


# ---------------------------------------------------------------------------
# the PR 6 bugs as runtime regressions
# ---------------------------------------------------------------------------


def test_reverted_copy_on_transfer_consumer_is_detected():
    """The PR 6 zero-copy consumer (asarray view read past release) is
    caught on EVERY seeded schedule once the poisoner scribbles —
    detection needs no lucky preemption."""
    for seed in range(5):
        with pytest.raises(RacesanError, match="corrupted"):
            racesan.exercise_queue(seed, consumer="alias", poison=True)


def test_buggy_producer_is_detected_under_schedule_sweep():
    with pytest.raises(ValueError, match="read-only"):
        racesan.exercise_publisher(0, buggy_producer=True)


def test_hardened_publisher_freezes_actor_views_and_spares_producer():
    """Satellite: PolicyPublisher.publish snapshots + freezes what it
    stores — an actor-side in-place write crashes even WITHOUT the
    poisoner, and the producer's own tree stays writable."""
    params = {"w": np.ones((2,), np.float32)}
    pub = PolicyPublisher(params, version=0)
    fresh = {"w": np.full((2,), 2.0, np.float32)}
    pub.publish(fresh, version=1)
    fresh["w"][0] = 9.0  # producer's retained tree: still writable
    version, stored = pub.get()
    assert version == 1
    assert float(stored["w"][0]) == 2.0  # snapshot taken BEFORE the 9.0
    with pytest.raises(ValueError, match="read-only"):
        stored["w"][0] = 3.0  # actor-side mutation crashes


def test_publisher_snapshot_handles_tuple_structured_params():
    """device_get params trees carry plain tuples AND NamedTuples —
    the frozen-snapshot copier must reconstruct both."""
    import collections

    Pair = collections.namedtuple("Pair", "w b")
    params = {
        "layers": (
            np.ones((2,), np.float32),
            Pair(np.ones((1,), np.float32), np.zeros((1,), np.float32)),
        ),
        "count": 3,
    }
    pub = PolicyPublisher(params, version=0)
    pub.publish(params, version=1)
    version, stored = pub.get()
    assert version == 1
    assert isinstance(stored["layers"], tuple)
    assert isinstance(stored["layers"][1], Pair)
    assert stored["count"] == 3
    with pytest.raises(ValueError, match="read-only"):
        stored["layers"][0][0] = 5.0
    with pytest.raises(ValueError, match="read-only"):
        stored["layers"][1].w[0] = 5.0


# ---------------------------------------------------------------------------
# the tier-1 fast profile
# ---------------------------------------------------------------------------


def test_quick_profile_sweeps_clean():
    out = racesan.quick_profile(schedules=100)
    assert out["schedules"] == 100
    assert out["races"] == 0
    # the sweep actually exercised all four units
    assert out["queue"]["consumed"] > 0
    assert out["publisher"]["reads"] > 0
    assert out["publisher"]["published"] > 0
    assert out["mailbox"]["deposits"] > 0
    assert out["mailbox"]["takes"] > 0
    assert out["batcher"]["responses"] > 0
    assert out["batcher"]["swaps"] > 0


# ---------------------------------------------------------------------------
# serving micro-batcher (ISSUE 10)
# ---------------------------------------------------------------------------


def test_batcher_exerciser_sweeps_clean_with_poison():
    """Request/flush/hot-swap interleavings over the serving
    MicroBatcher + PolicyStore: every response exact for the version it
    claims, per-client versions monotone, under the submit-freeze and
    swap-freeze poisoners."""
    out = racesan.exercise_sweep(
        range(12), lambda s: racesan.exercise_batcher(s, poison=True)
    )
    assert out["races"] == 0
    assert out["responses"] > 0 and out["swaps"] > 0
    # The /metrics scraper participant (ISSUE 16) actually interleaved:
    # gauge()+histogram snapshots read mid-swap/mid-flush on every
    # schedule, checked for torn/backwards histograms.
    assert out["scrapes"] > 0


def test_batcher_exerciser_replays_bit_identically():
    a = racesan.exercise_batcher(3, poison=True)
    b = racesan.exercise_batcher(3, poison=True)
    assert a == b


def test_aliasing_submit_is_detected_at_the_write_site():
    """A zero-copy submit under client buffer reuse (the PR 6 class at
    the serving handoff): the poisoner freezes the enqueued payload —
    which IS the client's buffer — so the client's next refill crashes
    at the write on every schedule."""
    for seed in range(3):
        with pytest.raises(ValueError, match="read-only"):
            racesan.exercise_batcher(seed, alias_submit=True, poison=True)


def test_copying_submit_tolerates_client_buffer_reuse():
    """The correct copy-on-submit under the SAME poisoner: the freeze
    lands on the batcher's own copy, the client's buffer stays
    writable, and the sweep is clean — reuse is the client contract."""
    out = racesan.exercise_batcher(0, poison=True)
    assert out["race_detected"] is False


def test_buggy_swapper_is_detected_at_the_write_site():
    """A swapper refreshing its RETAINED params tree in place after
    installing it — the write-after-publish class at the policy store —
    crashes at the write under freeze_on_swap on every schedule."""
    for seed in range(3):
        with pytest.raises(ValueError, match="read-only"):
            racesan.exercise_batcher(seed, buggy_swapper=True, poison=True)


# ---------------------------------------------------------------------------
# the multihost param mailbox (ISSUE 9 satellite)
# ---------------------------------------------------------------------------


def test_mailbox_exerciser_sweeps_clean_with_poison():
    out = racesan.exercise_sweep(
        range(10), lambda s: racesan.exercise_mailbox(s, poison=True)
    )
    assert out["races"] == 0
    assert out["deposits"] > 0 and out["takes"] > 0


def test_buggy_depositor_is_detected_at_the_write_site():
    """A mailbox writer refreshing its RETAINED tree in place after
    depositing — the write-after-publish class — crashes at the write
    under the poisoner on every schedule (frozen-snapshot contract,
    same as PolicyPublisher.publish)."""
    for seed in range(3):
        with pytest.raises(ValueError, match="read-only"):
            racesan.exercise_mailbox(seed, buggy_depositor=True)


def test_hardened_mailbox_freezes_consumer_view_and_spares_depositor():
    from actor_critic_tpu.parallel.multihost import ParamMailbox

    mb = ParamMailbox()
    tree = {"w": np.ones((2,), np.float32)}
    mb.deposit(tree, version=1, peer=0)
    tree["w"][0] = 9.0  # depositor's own tree: still writable
    version, peer, stored = mb.take()
    assert version == 1
    assert float(stored["w"][0]) == 1.0  # snapshot taken BEFORE the 9.0
    with pytest.raises(ValueError, match="read-only"):
        stored["w"][0] = 3.0


# ---------------------------------------------------------------------------
# the device trajectory ring (ISSUE 13)
# ---------------------------------------------------------------------------


def test_device_ring_exerciser_sweeps_clean_with_poison():
    """Actor-enqueue vs learner-gather interleavings over the REAL
    DeviceTrajRing (jitted enqueue + device gather) sweep clean under
    the leased-slot poisoner."""
    out = racesan.exercise_sweep(
        range(6), lambda s: racesan.exercise_device_ring(s, poison=True)
    )
    assert out["races"] == 0
    assert out["consumed"] > 0


def test_device_ring_exerciser_replays_bit_identically():
    a = racesan.exercise_device_ring(5, poison=True)
    b = racesan.exercise_device_ring(5, poison=True)
    assert a["consumed"] == b["consumed"]
    assert a["trace_len"] == b["trace_len"]


def test_device_ring_buggy_writer_is_caught_at_the_claim_site():
    """Reverting the leased-slot protection (drop-oldest reclaims a
    slot the learner still holds) trips the ring poisoner at the claim
    site on EVERY schedule — the device-plane write-after-publish
    class."""
    for seed in range(3):
        with pytest.raises(RacesanError, match="LEASED slot"):
            racesan.exercise_device_ring(
                seed, poison=True, buggy_writer=True
            )


def test_device_ring_release_before_read_is_detected():
    """The alias-class consumer (release, THEN read the slot) lets a
    drop-oldest overwrite land under the live read — the value check
    catches it within a short seed sweep, and the detecting seed
    replays."""
    detected = None
    for seed in range(16):
        try:
            racesan.exercise_device_ring(
                seed, poison=True, consumer="released",
                blocks_per_producer=4, depth=1,
            )
        except RacesanError:
            detected = seed
            break
    assert detected is not None, "no schedule exposed the stale read"
    with pytest.raises(RacesanError):
        racesan.exercise_device_ring(
            detected, poison=True, consumer="released",
            blocks_per_producer=4, depth=1,
        )
