"""scripts/bench_trend.py: the round driver's multi-metric trend view
(ROADMAP "Bench resilience", ISSUE 8 satellite) — wrapper and raw round
formats parse, the cpu_metrics block trends as rows (union across
rounds), dead-tunnel headlines show last_green, malformed files degrade
to `?` columns instead of crashing."""

import importlib.util
import json
from pathlib import Path

REPO = Path(__file__).parent.parent


def _load():
    spec = importlib.util.spec_from_file_location(
        "bench_trend", REPO / "scripts" / "bench_trend.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_rounds(root: Path):
    # r01: driver wrapper, dead tunnel, cpu_metrics present, last_green.
    rec1 = {
        "metric": "a2c", "value": 0.0, "error": "tunnel dead",
        "last_green": {"value": 2.6e10},
        "cpu_metrics": {
            "host_pool_scaling": {"value": 3.0},
            "update_wall": {"error": "rc=1: boom"},
        },
    }
    (root / "BENCH_r01.json").write_text(
        json.dumps({"n": 1, "rc": 1, "parsed": rec1}, indent=2)
    )
    # r02: raw bench.py line format, green, adds a NEW metric.
    rec2 = {
        "metric": "a2c", "value": 123456.0,
        "cpu_metrics": {
            "host_pool_scaling": {"value": 2.9},
            "update_wall": {"value": 10.4},
            "replay_sample_throughput": {"value": 2.07e6},
        },
    }
    (root / "BENCH_r02.json").write_text(json.dumps(rec2) + "\n")
    # r03: malformed.
    (root / "BENCH_r03.json").write_text("{not json")


def test_trend_rows_union_and_cells(tmp_path):
    mod = _load()
    _write_rounds(tmp_path)
    rounds, rows = mod.trend_rows(str(tmp_path))
    assert rounds == [1, 2, 3]
    table = dict(rows)
    # Headline: dead w/ last_green, green value, unparseable.
    assert table["tpu_headline"][0].startswith("dead (lg")
    assert table["tpu_headline"][1] != "dead"
    assert table["tpu_headline"][2] == "?"
    # Union of metric names across rounds; '-' before a metric existed,
    # 'err' where a round's subprocess failed.
    assert table["host_pool_scaling"] == ["3", "2.9", "?"]
    assert table["update_wall"][0] == "err"
    assert table["replay_sample_throughput"][0] == "-"
    assert table["replay_sample_throughput"][1] != "-"


def _write_guarded_rounds(root: Path):
    """r01 before guarded_ms existed, r02 carrying it, r03 malformed
    (guarded_ms a string), r04 the whole entry a failed subprocess."""
    (root / "BENCH_r01.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"update_wall": {"value": 8.0}},
    }) + "\n")
    (root / "BENCH_r02.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"update_wall": {
            "value": 8.1, "guarded_ms": 8.9, "guard_overhead_x": 1.1,
        }},
    }) + "\n")
    (root / "BENCH_r03.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"update_wall": {
            "value": 8.2, "guarded_ms": "oops",
        }},
    }) + "\n")
    (root / "BENCH_r04.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"update_wall": {"error": "rc=1: boom"}},
    }) + "\n")


def test_update_wall_guarded_sub_row(tmp_path):
    """ISSUE 14 satellite: guarded_ms trends as an update_wall sub-row
    — '-' before the field existed, '?' where it is malformed, 'err'
    when the whole metric subprocess failed."""
    mod = _load()
    _write_guarded_rounds(tmp_path)
    _rounds, rows = mod.trend_rows(str(tmp_path))
    table = dict(rows)
    assert table["update_wall.guarded_ms"] == ["-", "8.9", "?", "err"]


def _write_budget_counter_rounds(root: Path):
    """r01 before the counters existed, r02 carrying them, r03
    malformed (a counter is a string), r04 a failed subprocess."""
    (root / "BENCH_r01.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"update_wall": {"value": 8.0}},
    }) + "\n")
    (root / "BENCH_r02.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"update_wall": {
            "value": 8.1, "dispatches_per_block": 1,
            "device_transferred_bytes_per_block": 4,
        }},
    }) + "\n")
    (root / "BENCH_r03.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"update_wall": {
            "value": 8.2, "dispatches_per_block": "oops",
            "device_transferred_bytes_per_block": None,
        }},
    }) + "\n")
    (root / "BENCH_r04.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"update_wall": {"error": "rc=1: boom"}},
    }) + "\n")


def test_update_wall_budget_counter_sub_rows(tmp_path):
    """ISSUE 15 satellite: the perfsan dispatch/transfer actuals trend
    as update_wall sub-rows — '-' before the fields existed, '?' where
    malformed, 'err' when the whole metric subprocess failed."""
    mod = _load()
    _write_budget_counter_rounds(tmp_path)
    _rounds, rows = mod.trend_rows(str(tmp_path))
    table = dict(rows)
    assert table["update_wall.dispatches_per_block"] == [
        "-", "1", "?", "err",
    ]
    assert table["update_wall.device_transferred_bytes_per_block"] == [
        "-", "4", "?", "err",
    ]


def _write_fused_update_rounds(root: Path):
    """r01 before the metric existed, r02 a full fused-consume record,
    r03 malformed (walls are strings / None), r04 a failed subprocess."""
    (root / "BENCH_r01.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"update_wall": {"value": 8.0}},
    }) + "\n")
    (root / "BENCH_r02.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"fused_update_wall": {
            "value": 4.2, "fused_ms": 4.2, "unfused_ms": 4.9,
            "speedup_x": 1.17, "bf16_ms": 3.6, "fp32_ms": 4.1,
        }},
    }) + "\n")
    (root / "BENCH_r03.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"fused_update_wall": {
            "value": 4.3, "fused_ms": "oops", "speedup_x": None,
            "bf16_ms": {"nested": True},
        }},
    }) + "\n")
    (root / "BENCH_r04.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"fused_update_wall": {"error": "rc=1: boom"}},
    }) + "\n")


def test_fused_update_wall_sub_rows(tmp_path):
    """ISSUE 19 satellite: the fused-consume record expands into
    fused_ms / bf16_ms / speedup_x sub-rows — '-' before the metric
    existed, '?' where malformed, 'err' when the subprocess failed."""
    mod = _load()
    _write_fused_update_rounds(tmp_path)
    _rounds, rows = mod.trend_rows(str(tmp_path))
    table = dict(rows)
    assert table["fused_update_wall"] == ["-", "4.2", "4.3", "err"]
    assert table["fused_update_wall.fused_ms"] == ["-", "4.2", "?", "err"]
    assert table["fused_update_wall.bf16_ms"] == ["-", "3.6", "?", "err"]
    assert table["fused_update_wall.speedup_x"] == [
        "-", "1.17", "?", "err",
    ]
    # sub-rows sit directly under their parent row
    labels = [name for name, _ in rows]
    i = labels.index("fused_update_wall")
    assert labels[i + 1:i + 4] == [
        "fused_update_wall.fused_ms",
        "fused_update_wall.bf16_ms",
        "fused_update_wall.speedup_x",
    ]


def _write_multihost_rounds(root: Path):
    """r01 without the metric, r02 a full multihost record, r03 a
    malformed one (sync curve not a dict), r04 an unparseable file."""
    (root / "BENCH_r01.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"host_pool_scaling": {"value": 3.0}},
    }) + "\n")
    (root / "BENCH_r02.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "multihost_scaling": {
                "value": 1.95,
                "sync": {
                    "1": {"aggregate_steps_per_s": 94.2},
                    "2": {"aggregate_steps_per_s": 162.6},
                    "4": {"aggregate_steps_per_s": 184.0},
                },
                "straggler": {"gossip_over_sync": 2.01},
                "fault_injection": {"time_to_recover_s": 8.41},
            },
        },
    }) + "\n")
    (root / "BENCH_r03.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "multihost_scaling": {
                "value": 0.5, "sync": "oops",
                "straggler": {"gossip_over_sync": None},
                "fault_injection": {"error": "FleetSanError: rejoin"},
            },
        },
    }) + "\n")
    (root / "BENCH_r04.json").write_text("{not json")


def test_multihost_per_process_rows(tmp_path):
    """ISSUE 9 satellite: the multihost_scaling record expands into one
    sub-row per sync process count plus the straggler ratio; '-' before
    the metric existed, '?' for malformed sub-records."""
    mod = _load()
    _write_multihost_rounds(tmp_path)
    rounds, rows = mod.trend_rows(str(tmp_path))
    assert rounds == [1, 2, 3, 4]
    table = dict(rows)
    assert table["multihost_scaling"] == ["-", "1.95", "0.5", "?"]
    assert table["multihost_scaling.p1"] == ["-", "94.2", "?", "?"]
    assert table["multihost_scaling.p2"] == ["-", "162.6", "?", "?"]
    assert table["multihost_scaling.p4"] == ["-", "184", "?", "?"]
    assert table["multihost_scaling.straggler_gossip_x"] == [
        "-", "2.01", "?", "?",
    ]
    # ISSUE 12 satellite: wall time-to-recover after an injected host
    # kill; '-' before the fault-injection block existed, 'err' where
    # the chaos run itself failed.
    assert table["multihost_scaling.recover_s"] == [
        "-", "8.41", "err", "?",
    ]
    # Sub-rows sit directly under the main multihost row.
    labels = [label for label, _ in rows]
    main = labels.index("multihost_scaling")
    assert labels[main + 1 : main + 4] == [
        "multihost_scaling.p1", "multihost_scaling.p2",
        "multihost_scaling.p4",
    ]


def test_render_and_cli(tmp_path, capsys):
    mod = _load()
    _write_rounds(tmp_path)
    assert mod.main(["--root", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "r01" in out and "r03" in out
    assert "replay_sample_throughput" in out
    assert mod.main(["--root", str(tmp_path), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["rounds"] == [1, 2, 3]
    assert "host_pool_scaling" in payload["rows"]


def test_empty_root(tmp_path, capsys):
    mod = _load()
    assert mod.main(["--root", str(tmp_path)]) == 0
    assert "no BENCH_r" in capsys.readouterr().out


def test_parses_committed_rounds():
    """The real repo-root BENCH_r*.json history must parse (wrapper
    format with parsed/tail): at least one round resolves to a real
    record rather than '?'."""
    mod = _load()
    rounds, rows = mod.trend_rows(str(REPO))
    assert rounds, "no committed rounds found"
    headline = dict(rows)["tpu_headline"]
    assert any(c != "?" for c in headline), headline


def test_serving_latency_sub_rows(tmp_path):
    """ISSUE 10 satellite: serving_latency expands into micro-batched
    actions/s + p50/p99 sub-rows; '-' before the metric existed, '?'
    for malformed sub-records, 'err' for failed subprocesses."""
    mod = _load()
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"host_pool_scaling": {"value": 3.0}},
    }) + "\n")
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "serving_latency": {
                "value": 6.6,
                "micro_batched": {
                    "actions_per_s": 445.6, "p50_ms": 66.2,
                    "p99_ms": 182.4,
                },
            },
        },
    }) + "\n")
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "serving_latency": {"value": 1.0, "micro_batched": "oops"},
        },
    }) + "\n")
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"serving_latency": {"error": "rc=1"}},
    }) + "\n")
    # r05: carries the ISSUE 16 histogram-derived fields — one of them
    # malformed (a string where a number belongs).
    (tmp_path / "BENCH_r05.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "serving_latency": {
                "value": 5.1,
                "micro_batched": {
                    "actions_per_s": 400.0, "p50_ms": 70.0,
                    "p99_ms": 190.0, "slo_burn": 0.25,
                    "hist_p50_ms": 68.4, "hist_p99_ms": "garbage",
                },
            },
        },
    }) + "\n")
    rounds, rows = mod.trend_rows(str(tmp_path))
    assert rounds == [1, 2, 3, 4, 5]
    table = dict(rows)
    assert table["serving_latency"] == ["-", "6.6", "1", "err", "5.1"]
    assert table["serving_latency.actions_per_s"] == [
        "-", "445.6", "?", "err", "400",
    ]
    assert table["serving_latency.p50_ms"] == [
        "-", "66.2", "?", "err", "70",
    ]
    assert table["serving_latency.p99_ms"] == [
        "-", "182.4", "?", "err", "190",
    ]
    # ISSUE 16 sub-rows: rounds predating the fields render '?', the
    # malformed hist_p99_ms cell degrades to '?' instead of crashing.
    assert table["serving_latency.slo_burn"] == [
        "-", "?", "?", "err", "0.25",
    ]
    assert table["serving_latency.hist_p50_ms"] == [
        "-", "?", "?", "err", "68.4",
    ]
    assert table["serving_latency.hist_p99_ms"] == [
        "-", "?", "?", "err", "?",
    ]
    labels = [label for label, _ in rows]
    i = labels.index("serving_latency")
    assert labels[i + 1:i + 7] == [
        "serving_latency.actions_per_s",
        "serving_latency.p50_ms",
        "serving_latency.p99_ms",
        "serving_latency.slo_burn",
        "serving_latency.hist_p50_ms",
        "serving_latency.hist_p99_ms",
    ]


def test_scenario_fleet_sub_rows(tmp_path):
    """ISSUE 11 satellite: scenario_fleet expands into the mixture
    steps/s, one homogeneous-fleet sub-row per member type (union
    across rounds), and the instance-sweep peak; '-' before the mixture
    block existed (the PR 8 homogeneous-only record), '?' for malformed
    sub-records, 'err' for failed subprocesses."""
    mod = _load()
    # r01: the PR 8 record — scenario_fleet without a mixture block.
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"scenario_fleet": {"value": 280000.0}},
    }) + "\n")
    # r02: the full ISSUE 11 record.
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "scenario_fleet": {
                "value": 275000.0,
                "mixture": {
                    "steps_per_s": 61000.0,
                    "per_type_steps_per_s": {
                        "cartpole": 240000.0, "pendulum": 250000.0,
                        "acrobot": 90000.0, "maze": 120000.0,
                    },
                    "overhead_vs_series_x": 0.7,
                },
                "instance_sweep": {
                    "curve": {"256": 20000.0, "512": 40000.0},
                    "peak_instances": 512,
                    "peak_steps_per_s": 40000.0,
                },
            },
        },
    }) + "\n")
    # r03: malformed mixture/sweep blocks degrade to '?'.
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "scenario_fleet": {
                "value": 1.0, "mixture": "oops", "instance_sweep": 3,
            },
        },
    }) + "\n")
    # r04: the whole metric's subprocess failed.
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"scenario_fleet": {"error": "rc=1"}},
    }) + "\n")
    rounds, rows = mod.trend_rows(str(tmp_path))
    assert rounds == [1, 2, 3, 4]
    table = dict(rows)
    assert table["scenario_fleet"] == ["2.8e+05", "2.75e+05", "1", "err"]
    assert table["scenario_fleet.mixture"] == ["-", "6.1e+04", "?", "err"]
    assert table["scenario_fleet.cartpole"] == ["-", "2.4e+05", "?", "err"]
    assert table["scenario_fleet.maze"] == ["-", "1.2e+05", "?", "err"]
    assert table["scenario_fleet.sweep_peak"] == ["-", "4e+04", "?", "err"]
    labels = [label for label, _ in rows]
    i = labels.index("scenario_fleet")
    assert labels[i + 1] == "scenario_fleet.mixture"
    assert "scenario_fleet.acrobot" in labels


def test_serving_fleet_scaling_sub_rows(tmp_path):
    """ISSUE 17 satellite: serving_fleet_scaling expands into per-
    replica-count actions/s + p99 sub-rows (union across rounds); '-'
    before the metric existed or a count was dropped, '?' for malformed
    sub-records, 'err' for failed subprocesses."""
    mod = _load()
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"host_pool_scaling": {"value": 3.0}},
    }) + "\n")
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "serving_fleet_scaling": {
                "value": 1.96,
                "points": [
                    {"replicas": 1, "actions_per_s": 610.0,
                     "p99_ms": 61.0},
                    {"replicas": 2, "actions_per_s": 1001.4,
                     "p99_ms": 55.3},
                    {"replicas": 3, "actions_per_s": 1195.2,
                     "p99_ms": 51.2},
                ],
            },
        },
    }) + "\n")
    # r03: points block malformed; r04: a point carries a non-numeric
    # field and a count (r2) is absent from the curve.
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "serving_fleet_scaling": {"value": 0.9, "points": "oops"},
        },
    }) + "\n")
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "serving_fleet_scaling": {
                "value": 1.5,
                "points": [
                    {"replicas": 1, "actions_per_s": 600.0,
                     "p99_ms": 62.0},
                    {"replicas": 3, "actions_per_s": "garbage"},
                ],
            },
        },
    }) + "\n")
    (tmp_path / "BENCH_r05.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"serving_fleet_scaling": {"error": "rc=1"}},
    }) + "\n")
    rounds, rows = mod.trend_rows(str(tmp_path))
    assert rounds == [1, 2, 3, 4, 5]
    table = dict(rows)
    assert table["serving_fleet_scaling"] == [
        "-", "1.96", "0.9", "1.5", "err",
    ]
    assert table["serving_fleet_scaling.r1"] == [
        "-", "610", "?", "600", "err",
    ]
    assert table["serving_fleet_scaling.r2"] == [
        "-", "1001.4", "?", "-", "err",
    ]
    assert table["serving_fleet_scaling.r3"] == [
        "-", "1195.2", "?", "?", "err",
    ]
    # p99 of the r3 point is absent in r04 — malformed, not missing.
    assert table["serving_fleet_scaling.r3.p99_ms"] == [
        "-", "51.2", "?", "?", "err",
    ]
    labels = [label for label, _ in rows]
    i = labels.index("serving_fleet_scaling")
    assert labels[i + 1:i + 3] == [
        "serving_fleet_scaling.r1", "serving_fleet_scaling.r1.p99_ms",
    ]


def _write_data_plane_rounds(root: Path):
    """r01 without the metric, r02 a full data-plane A/B record, r03 a
    malformed one, r04 unparseable."""
    (root / "BENCH_r01.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"host_pool_scaling": {"value": 3.0}},
    }) + "\n")
    (root / "BENCH_r02.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "consumed_env_steps_per_s": {
                "value": 1210.6,
                "host": {"consumed_steps_per_s": 808.3},
                "device": {"consumed_steps_per_s": 1210.6},
                "per_block_transfer_bytes": {
                    "host_per_consumed_block": 7232,
                    "device_per_consumed_block": 0,
                    "device_enqueue_per_block": 2960,
                    "host_measured": 7232,
                    "enqueue_measured": "oops",
                },
            },
        },
    }) + "\n")
    (root / "BENCH_r03.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "consumed_env_steps_per_s": {
                "value": 0.5, "host": "oops", "device": {},
                "per_block_transfer_bytes": [],
            },
        },
    }) + "\n")
    (root / "BENCH_r04.json").write_text("{not json")


def test_data_plane_sub_rows(tmp_path):
    """ISSUE 13 satellite: the consumed_env_steps_per_s record expands
    into per-plane steps/s sub-rows plus the device enqueue bytes; '-'
    before the metric existed, '?' for malformed sub-records."""
    mod = _load()
    _write_data_plane_rounds(tmp_path)
    rounds, rows = mod.trend_rows(str(tmp_path))
    assert rounds == [1, 2, 3, 4]
    table = dict(rows)
    assert table["consumed_env_steps_per_s"] == ["-", "1210.6", "0.5", "?"]
    assert table["consumed_env_steps_per_s.host"] == ["-", "808.3", "?", "?"]
    assert table["consumed_env_steps_per_s.device"] == [
        "-", "1210.6", "?", "?",
    ]
    assert table["consumed_env_steps_per_s.enqueue_bytes"] == [
        "-", "2960", "?", "?",
    ]
    # ISSUE 15: the METERED actuals trend too — '-' before the fields
    # existed, '?' where a counter is malformed.
    assert table["consumed_env_steps_per_s.host_measured"] == [
        "-", "7232", "?", "?",
    ]
    assert table["consumed_env_steps_per_s.enqueue_measured"] == [
        "-", "?", "?", "?",
    ]
    labels = [label for label, _ in rows]
    main = labels.index("consumed_env_steps_per_s")
    assert labels[main + 1 : main + 6] == [
        "consumed_env_steps_per_s.host",
        "consumed_env_steps_per_s.device",
        "consumed_env_steps_per_s.enqueue_bytes",
        "consumed_env_steps_per_s.host_measured",
        "consumed_env_steps_per_s.enqueue_measured",
    ]


def test_pad_overhead_sub_rows(tmp_path):
    """ISSUE 20 satellite: pad_overhead expands into per-shape
    overhead_x sub-rows (Pallas ragged lanes + serving backfill sizes);
    '-' before the metric existed, '?' for malformed sub-records, 'err'
    for failed subprocesses."""
    mod = _load()
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"host_pool_scaling": {"value": 3.0}},
    }) + "\n")
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "pad_overhead": {
                "value": 1.31,
                "pallas": {
                    "E7": {"overhead_x": 1.02},
                    "E96": {"overhead_x": 1.05},
                    "E200": {"overhead_x": 1.31},
                },
                "serving": {
                    "n3": {"overhead_x": 1.11},
                    "n5": {"overhead_x": 1.08},
                },
            },
        },
    }) + "\n")
    # r03: present but malformed — the pallas group is a string, one
    # serving pair lost its overhead_x, the other pair isn't a dict.
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {
            "pad_overhead": {
                "value": 1.0,
                "pallas": "oops",
                "serving": {
                    "n3": {"padded_us": 9.0},
                    "n5": "oops",
                },
            },
        },
    }) + "\n")
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({
        "metric": "a2c", "value": 1.0,
        "cpu_metrics": {"pad_overhead": {"error": "rc=1"}},
    }) + "\n")
    rounds, rows = mod.trend_rows(str(tmp_path))
    assert rounds == [1, 2, 3, 4]
    table = dict(rows)
    assert table["pad_overhead"] == ["-", "1.31", "1", "err"]
    assert table["pad_overhead.pallas_E7"] == ["-", "1.02", "?", "err"]
    assert table["pad_overhead.pallas_E96"] == ["-", "1.05", "?", "err"]
    assert table["pad_overhead.pallas_E200"] == [
        "-", "1.31", "?", "err",
    ]
    assert table["pad_overhead.serving_n3"] == ["-", "1.11", "?", "err"]
    assert table["pad_overhead.serving_n5"] == ["-", "1.08", "?", "err"]
    labels = [label for label, _ in rows]
    i = labels.index("pad_overhead")
    assert labels[i + 1:i + 6] == [
        "pad_overhead.pallas_E7",
        "pad_overhead.pallas_E96",
        "pad_overhead.pallas_E200",
        "pad_overhead.serving_n3",
        "pad_overhead.serving_n5",
    ]
