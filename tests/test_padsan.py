"""Tier-1 wiring for padsan (ISSUE 20 runtime half).

Mirrors test_racesan/test_numsan's layers: (1) every guarded program
sweeps clean under pad-lane poison, (2) a seed replays bit-identically
(the `digest` contract), (3) both reverted modes (`unmasked-mean`,
`no-slice`) are caught deterministically on EVERY schedule, (4) the
monkeypatched seams are restored even when the exerciser raises, (5)
the CLI's exit codes stay distinct.
"""

import importlib.util
from pathlib import Path

import pytest

from actor_critic_tpu.analysis import padsan

REPO = Path(__file__).parent.parent

EXERCISERS = {
    "chunked": padsan.exercise_chunked,
    "pallas": padsan.exercise_pallas,
    "mixture": padsan.exercise_mixture,
    "serving": padsan.exercise_serving,
    "device-plane": padsan.exercise_device_plane,
}


def _load_cli():
    spec = importlib.util.spec_from_file_location(
        "padsan_cli", REPO / "scripts" / "padsan.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# clean sweeps: poisoned pads are bitwise unobservable
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("scenario", sorted(EXERCISERS))
def test_scenario_sweeps_clean(scenario):
    out = padsan.exercise_sweep(range(0, 3), EXERCISERS[scenario])
    assert out["violations"] == 0
    assert out["schedules"] == 3
    # every schedule ran the real program twice (A zero-fill, B poison)
    assert out["programs"] == 3 * 2 * 2


def test_quick_profile_sweeps_clean():
    out = padsan.quick_profile(schedules=10, seed0=0)
    assert out["violations"] == 0
    assert out["schedules"] == 10
    for key in ("chunked", "pallas", "mixture", "serving", "device_plane"):
        assert out[key]["schedules"] >= 2
        assert out[key]["violations"] == 0


# ---------------------------------------------------------------------------
# bit-identical replay per seed
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("scenario", sorted(EXERCISERS))
def test_replay_is_bit_identical_per_seed(scenario):
    fn = EXERCISERS[scenario]
    a, b = fn(11), fn(11)
    assert a["digest"] == b["digest"]
    assert a["trace"] == b["trace"]
    # a different seed must be allowed to differ (no vacuous equality)
    assert fn(12)["digest"] != a["digest"]


# ---------------------------------------------------------------------------
# reverted modes: caught deterministically on EVERY schedule
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("scenario", sorted(EXERCISERS))
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_reverted_unmasked_mean_detected(scenario, seed):
    with pytest.raises(padsan.PadSanError, match="REVERTED GUARD"):
        EXERCISERS[scenario](seed, revert="unmasked-mean")


@pytest.mark.parametrize("scenario", ["pallas", "serving"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_reverted_no_slice_detected(scenario, seed):
    with pytest.raises(padsan.PadSanError, match="REVERTED GUARD"):
        EXERCISERS[scenario](seed, revert="no-slice")


@pytest.mark.parametrize(
    "scenario", ["chunked", "mixture", "device-plane"]
)
def test_no_slice_is_rejected_where_it_means_nothing(scenario):
    # these seams have no full-width output to forget to slice; a typo'd
    # revert must be a usage error, not a vacuous pass
    with pytest.raises(ValueError, match="supports revert modes"):
        EXERCISERS[scenario](0, revert="no-slice")


def test_revert_mode_restores_the_seams():
    """The poison monkeypatches (`pallas_scan._pad_lanes`,
    `compile_cache.pad_to_bucket`) must be restored even when an
    exerciser raises — a leaked poisoned seam would corrupt every later
    dispatch in the process."""
    from actor_critic_tpu.ops import pallas_scan
    from actor_critic_tpu.utils import compile_cache

    orig_pad_lanes = pallas_scan._pad_lanes
    orig_bucket = compile_cache.pad_to_bucket
    with pytest.raises(padsan.PadSanError):
        padsan.exercise_pallas(0, revert="unmasked-mean")
    with pytest.raises(padsan.PadSanError):
        padsan.exercise_serving(0, revert="unmasked-mean")
    assert pallas_scan._pad_lanes is orig_pad_lanes
    assert compile_cache.pad_to_bucket is orig_bucket


# ---------------------------------------------------------------------------
# the masked-summary seam itself
# ---------------------------------------------------------------------------


def test_masked_summary_excludes_pad_lanes_nan_safely():
    import numpy as np

    x = np.array([1.0, 2.0, np.nan, np.inf], np.float64)
    mask = np.array([1.0, 1.0, 0.0, 0.0])
    a = padsan.masked_summary(x, mask)
    b = padsan.masked_summary(
        np.array([1.0, 2.0, 0.0, 0.0]), mask
    )
    assert a == b  # where-select: junk lanes never touch the sum
    assert padsan.masked_summary(x, mask, revert="unmasked-mean") != a


def test_fill_is_dtype_aware():
    import numpy as np

    assert padsan._fill("nan", np.float32) != padsan._fill(
        "big", np.float32
    ) or True  # nan compares unequal to everything; just exercise it
    assert padsan._fill("big", np.int8) == 127.0
    assert padsan._fill("-big", np.int8) == -128.0
    assert padsan._fill("int8sat", np.int32) == float(
        np.iinfo(np.int32).max
    )


# ---------------------------------------------------------------------------
# CLI exit codes
# ---------------------------------------------------------------------------


def test_cli_exit_codes(capsys):
    cli = _load_cli()
    assert cli.main(
        ["--scenario", "chunked", "--schedules", "2"]
    ) == 0
    assert cli.main(
        ["--scenario", "chunked", "--revert", "unmasked-mean",
         "--schedules", "1"]
    ) == 1
    assert cli.main(
        ["--scenario", "serving", "--revert", "no-slice",
         "--schedules", "1"]
    ) == 1
    # --revert without a single scenario, or against a seam that has
    # no slice-back, is a usage crash — not a clean run
    assert cli.main(["--revert", "unmasked-mean"]) == 2
    assert cli.main(
        ["--scenario", "mixture", "--revert", "no-slice"]
    ) == 2
    capsys.readouterr()


def test_cli_json_mode(capsys):
    import json

    cli = _load_cli()
    rc = cli.main(
        ["--scenario", "device-plane", "--schedules", "2", "--json"]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["schedules"] == 2
    assert out["violations"] == 0
