"""Sequence-parallel scans vs. the single-device golden scans.

Runs on the fake 8-device CPU mesh (conftest.py; SURVEY.md §4). The
time-sharded implementations in `parallel/seqpar.py` must reproduce the
plain `lax.scan` results of `ops/returns.py` bitwise-closely for every
recurrence, including across-segment episode terminations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.ops import returns
from actor_critic_tpu.parallel import seqpar

T, E = 64, 5  # T divides the 8-device mesh; E exercises batch broadcast
GAMMA, LAM = 0.99, 0.95


@pytest.fixture(scope="module")
def mesh():
    return seqpar.make_sp_mesh()


@pytest.fixture(scope="module")
def traj():
    rng = np.random.default_rng(0)
    rewards = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    values = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    # ~15% terminations, scattered so several land on segment boundaries.
    dones = jnp.asarray(rng.random(size=(T, E)) < 0.15, jnp.float32)
    bootstrap = jnp.asarray(rng.normal(size=(E,)), jnp.float32)
    return rewards, values, dones, bootstrap


def test_discounted_returns_matches_scan(mesh, traj):
    rewards, _, dones, bootstrap = traj
    golden = returns.discounted_returns(rewards, dones, bootstrap, GAMMA)
    fn = seqpar.make_seqpar_fn(
        seqpar.seqpar_discounted_returns, mesh, n_time_sharded_args=2
    )
    got = fn(rewards, dones, bootstrap, GAMMA)
    np.testing.assert_allclose(np.asarray(got), np.asarray(golden), rtol=1e-5, atol=1e-5)


def test_gae_matches_scan(mesh, traj):
    rewards, values, dones, bootstrap = traj
    adv_g, ret_g = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    fn = seqpar.make_seqpar_fn(seqpar.seqpar_gae, mesh, n_time_sharded_args=3)
    adv, ret = fn(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ret), np.asarray(ret_g), rtol=1e-5, atol=1e-5)


def test_vtrace_matches_scan(mesh, traj):
    rewards, values, dones, bootstrap = traj
    rng = np.random.default_rng(1)
    target_lp = jnp.asarray(rng.normal(size=(T, E)) * 0.3, jnp.float32)
    behav_lp = jnp.asarray(rng.normal(size=(T, E)) * 0.3, jnp.float32)

    golden = returns.vtrace(
        target_lp, behav_lp, rewards, values, dones, bootstrap,
        GAMMA, rho_bar=1.0, c_bar=1.0, lam=0.9,
    )
    fn = seqpar.make_seqpar_fn(seqpar.seqpar_vtrace, mesh, n_time_sharded_args=5)
    got = fn(target_lp, behav_lp, rewards, values, dones, bootstrap, GAMMA, 1.0, 1.0, 0.9)

    for name in ("vs", "pg_advantages", "clipped_rhos"):
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(golden, name)),
            rtol=1e-5, atol=1e-5, err_msg=name,
        )


def test_gae_no_dones_boundary(mesh):
    """All-zero dones: segment products are maximal, stressing the chain."""
    rewards = jnp.ones((T, 1), jnp.float32)
    values = jnp.zeros((T, 1), jnp.float32)
    dones = jnp.zeros((T, 1), jnp.float32)
    bootstrap = jnp.zeros((1,), jnp.float32)
    adv_g, _ = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    fn = seqpar.make_seqpar_fn(seqpar.seqpar_gae, mesh, n_time_sharded_args=3)
    adv, _ = fn(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-5, atol=1e-5)


def test_long_trajectory_many_segments(mesh):
    """A long (T=4096) trajectory — the long-context case the sharding is
    for — still matches the golden scan."""
    Tl = 4096
    rng = np.random.default_rng(2)
    rewards = jnp.asarray(rng.normal(size=(Tl,)), jnp.float32)
    values = jnp.asarray(rng.normal(size=(Tl,)), jnp.float32)
    dones = jnp.asarray(rng.random(size=(Tl,)) < 0.01, jnp.float32)
    bootstrap = jnp.asarray(0.3, jnp.float32)
    adv_g, ret_g = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    fn = seqpar.make_seqpar_fn(seqpar.seqpar_gae, mesh, n_time_sharded_args=3)
    adv, ret = fn(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(ret), np.asarray(ret_g), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("dp_axis", [None, "dp"], ids=["sp-1d", "sp2xdp4-2d"])
def test_sp_train_step_rollout_to_update_one_program(dp_axis):
    """END-TO-END sp trainer: `impala.make_sp_train_step` runs rollout →
    resharding → sequence-parallel update → actor refresh as ONE jitted
    program, and over several iterations stays equivalent to the
    unsharded `make_train_step` — the trainer really PRODUCES the long
    trajectory the sp learner consumes (VERDICT r3 weak #6), rather than
    being fed a synthetic one."""
    from actor_critic_tpu.algos import impala
    from actor_critic_tpu.envs import make_two_state_mdp

    if dp_axis is not None and not hasattr(jax, "shard_map"):
        pytest.skip(
            "jax<0.5 compat path (experimental shard_map + "
            "with_sharding_constraint standing in for reshard): the fused "
            "rollout on the 2-D sp×dp mesh lays the env axis out "
            "differently, which bitwise-shifts sampled actions vs the "
            "unsharded golden run; the sp-1d fused equivalence and the "
            "standalone 2-D update equivalence below both still pass"
        )
    env = make_two_state_mdp()
    # Long rollout relative to the env (horizon 8): T=64 spans many
    # episodes and divides both mesh layouts' sp size (8 and 2).
    cfg = impala.ImpalaConfig(
        num_envs=8, rollout_steps=64, hidden=(16,), actor_refresh_every=2
    )
    if dp_axis is None:
        m = seqpar.make_sp_mesh()
    else:
        m = jax.make_mesh((2, 4), (seqpar.SP_AXIS, dp_axis))

    golden_step = jax.jit(impala.make_train_step(env, cfg))
    sp_step = impala.make_sp_train_step(env, cfg, m, dp_axis_name=dp_axis)

    state_g = impala.init_state(env, cfg, jax.random.key(0))
    state_sp = impala.init_state(env, cfg, jax.random.key(0))
    for _ in range(3):
        state_g, metrics_g = golden_step(state_g)
        state_sp, metrics_sp = sp_step(state_sp)

    # Same rollouts (same PRNG stream) through either update path ⇒ the
    # learner params, the STALE actor params (refresh cadence), and the
    # scalar metrics must all agree across three compounding iterations.
    for name, a, b in (
        ("params", state_g.params, state_sp.params),
        ("actor_params", state_g.actor_params, state_sp.actor_params),
    ):
        jax.tree.map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=2e-4, atol=1e-5,
                err_msg=name,
            ),
            a, b,
        )
    # Identical metric SURFACE (same derived keys via aggregate_metrics)
    # and matching values for the scalar learner metrics.
    assert set(metrics_sp) == set(metrics_g)
    for k in ("loss", "mean_rho", "avg_return_ema", "mean_finished_return",
              "mean_ep_length"):
        np.testing.assert_allclose(
            float(metrics_sp[k]), float(metrics_g[k]), rtol=1e-4, atol=1e-6,
            err_msg=k,
        )
    assert int(state_sp.update_step) == 3


@pytest.mark.parametrize("dp_axis", [None, "dp"], ids=["sp-1d", "sp2xdp4-2d"])
def test_sp_impala_update_matches_unsharded(dp_axis):
    """The sequence-parallel IMPALA learner update (impala.make_sp_update)
    produces the SAME post-update params as the unsharded impala_loss +
    optimizer step on an identical long trajectory — the trainer-level
    integration the standalone seqpar_* golden tests don't cover. Runs in
    both mesh layouts: 1-D sp (8 time shards) and 2-D sp×dp (2 time × 4
    env shards, gradients/metrics reduced over both axes)."""
    import optax

    from actor_critic_tpu.algos import impala
    from actor_critic_tpu.algos.common import Transition
    from actor_critic_tpu.envs import make_two_state_mdp

    env = make_two_state_mdp()
    cfg = impala.ImpalaConfig(num_envs=8, rollout_steps=512, hidden=(16,))
    Tl, El = 512, 8
    rng = np.random.default_rng(3)
    traj = Transition(
        obs=jnp.asarray(rng.random((Tl, El, 2)), jnp.float32),
        action=jnp.asarray(rng.integers(0, 2, (Tl, El))),
        log_prob=jnp.asarray(rng.normal(size=(Tl, El)) * 0.3, jnp.float32),
        value=jnp.zeros((Tl, El)),
        reward=jnp.asarray(rng.random((Tl, El)), jnp.float32),
        done=jnp.asarray(rng.random((Tl, El)) < 0.1, jnp.float32),
        terminated=jnp.asarray(rng.random((Tl, El)) < 0.05, jnp.float32),
        final_obs=jnp.asarray(rng.random((Tl, El, 2)), jnp.float32),
    )
    traj = traj._replace(
        terminated=jnp.minimum(traj.terminated, traj.done)  # term => done
    )
    bootstrap_obs = jnp.asarray(rng.random((El, 2)), jnp.float32)

    net = impala.make_network(env, cfg)
    opt = impala.make_optimizer(cfg)
    params = net.init(jax.random.key(0), jnp.zeros((1, 2)))
    opt_state = opt.init(params)

    # Unsharded golden update.
    (_, metrics_g), grads = jax.value_and_grad(impala.impala_loss, has_aux=True)(
        params, net.apply, traj, bootstrap_obs, cfg, True
    )
    upd, _ = opt.update(grads, opt_state, params)
    params_g = optax.apply_updates(params, upd)

    if dp_axis is None:
        m = seqpar.make_sp_mesh()
    else:
        m = jax.make_mesh((2, 4), (seqpar.SP_AXIS, dp_axis))
    sp_update = impala.make_sp_update(env, cfg, m, dp_axis_name=dp_axis)
    params_sp, _, metrics_sp = sp_update(params, opt_state, traj, bootstrap_obs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        params_g,
        params_sp,
    )
    np.testing.assert_allclose(
        float(metrics_sp["loss"]), float(metrics_g["loss"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(metrics_sp["mean_rho"]), float(metrics_g["mean_rho"]), rtol=1e-5
    )
