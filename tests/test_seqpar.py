"""Sequence-parallel scans vs. the single-device golden scans.

Runs on the fake 8-device CPU mesh (conftest.py; SURVEY.md §4). The
time-sharded implementations in `parallel/seqpar.py` must reproduce the
plain `lax.scan` results of `ops/returns.py` bitwise-closely for every
recurrence, including across-segment episode terminations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.ops import returns
from actor_critic_tpu.parallel import seqpar

T, E = 64, 5  # T divides the 8-device mesh; E exercises batch broadcast
GAMMA, LAM = 0.99, 0.95


@pytest.fixture(scope="module")
def mesh():
    return seqpar.make_sp_mesh()


@pytest.fixture(scope="module")
def traj():
    rng = np.random.default_rng(0)
    rewards = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    values = jnp.asarray(rng.normal(size=(T, E)), jnp.float32)
    # ~15% terminations, scattered so several land on segment boundaries.
    dones = jnp.asarray(rng.random(size=(T, E)) < 0.15, jnp.float32)
    bootstrap = jnp.asarray(rng.normal(size=(E,)), jnp.float32)
    return rewards, values, dones, bootstrap


def test_discounted_returns_matches_scan(mesh, traj):
    rewards, _, dones, bootstrap = traj
    golden = returns.discounted_returns(rewards, dones, bootstrap, GAMMA)
    fn = seqpar.make_seqpar_fn(
        seqpar.seqpar_discounted_returns, mesh, n_time_sharded_args=2
    )
    got = fn(rewards, dones, bootstrap, GAMMA)
    np.testing.assert_allclose(np.asarray(got), np.asarray(golden), rtol=1e-5, atol=1e-5)


def test_gae_matches_scan(mesh, traj):
    rewards, values, dones, bootstrap = traj
    adv_g, ret_g = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    fn = seqpar.make_seqpar_fn(seqpar.seqpar_gae, mesh, n_time_sharded_args=3)
    adv, ret = fn(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ret), np.asarray(ret_g), rtol=1e-5, atol=1e-5)


def test_vtrace_matches_scan(mesh, traj):
    rewards, values, dones, bootstrap = traj
    rng = np.random.default_rng(1)
    target_lp = jnp.asarray(rng.normal(size=(T, E)) * 0.3, jnp.float32)
    behav_lp = jnp.asarray(rng.normal(size=(T, E)) * 0.3, jnp.float32)

    golden = returns.vtrace(
        target_lp, behav_lp, rewards, values, dones, bootstrap,
        GAMMA, rho_bar=1.0, c_bar=1.0, lam=0.9,
    )
    fn = seqpar.make_seqpar_fn(seqpar.seqpar_vtrace, mesh, n_time_sharded_args=5)
    got = fn(target_lp, behav_lp, rewards, values, dones, bootstrap, GAMMA, 1.0, 1.0, 0.9)

    for name in ("vs", "pg_advantages", "clipped_rhos"):
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(golden, name)),
            rtol=1e-5, atol=1e-5, err_msg=name,
        )


def test_gae_no_dones_boundary(mesh):
    """All-zero dones: segment products are maximal, stressing the chain."""
    rewards = jnp.ones((T, 1), jnp.float32)
    values = jnp.zeros((T, 1), jnp.float32)
    dones = jnp.zeros((T, 1), jnp.float32)
    bootstrap = jnp.zeros((1,), jnp.float32)
    adv_g, _ = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    fn = seqpar.make_seqpar_fn(seqpar.seqpar_gae, mesh, n_time_sharded_args=3)
    adv, _ = fn(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-5, atol=1e-5)


def test_long_trajectory_many_segments(mesh):
    """A long (T=4096) trajectory — the long-context case the sharding is
    for — still matches the golden scan."""
    Tl = 4096
    rng = np.random.default_rng(2)
    rewards = jnp.asarray(rng.normal(size=(Tl,)), jnp.float32)
    values = jnp.asarray(rng.normal(size=(Tl,)), jnp.float32)
    dones = jnp.asarray(rng.random(size=(Tl,)) < 0.01, jnp.float32)
    bootstrap = jnp.asarray(0.3, jnp.float32)
    adv_g, ret_g = returns.gae(rewards, values, dones, bootstrap, GAMMA, LAM)
    fn = seqpar.make_seqpar_fn(seqpar.seqpar_gae, mesh, n_time_sharded_args=3)
    adv, ret = fn(rewards, values, dones, bootstrap, GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(adv), np.asarray(adv_g), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(ret), np.asarray(ret_g), rtol=1e-4, atol=1e-4)
