"""TrajQueue / PolicyPublisher unit contracts (ISSUE 6): FIFO with slot
recycling, drop-oldest back-pressure, staleness-bounded consumption, the
sampler gauge, and the run_report queue row."""

import importlib.util
import json
import threading
import time
from pathlib import Path

import numpy as np

from actor_critic_tpu.algos.traj_queue import PolicyPublisher, TrajQueue
from actor_critic_tpu.telemetry import sampler


def _block(v: float, shape=(4, 2)) -> dict:
    return {"obs": np.full(shape, v, np.float32),
            "reward": np.full(shape[:1], v, np.float32)}


def test_fifo_and_copy_semantics():
    q = TrajQueue(depth=3, register_gauge=False)
    src = _block(1.0)
    assert q.put(src, version=0)
    src["obs"][:] = 99.0  # the queue must have snapshotted
    assert q.put(_block(2.0), version=1)
    b1 = q.get(timeout=1.0)
    assert b1 is not None and b1.version == 0 and b1.seq == 0
    np.testing.assert_array_equal(b1.arrays["obs"], 1.0)
    q.release(b1)
    b2 = q.get(timeout=1.0)
    assert b2.version == 1
    np.testing.assert_array_equal(b2.arrays["obs"], 2.0)
    q.release(b2)
    assert q.get(timeout=0.05) is None  # empty: timeout, not a hang


def test_slot_recycling_reuses_storage():
    q = TrajQueue(depth=2, register_gauge=False)
    q.put(_block(1.0), version=0)
    b = q.get(timeout=1.0)
    storage = b.arrays["obs"]
    q.release(b)
    q.put(_block(2.0), version=1)
    b2 = q.get(timeout=1.0)
    # Same preallocated array object, new contents: alloc-free steady state.
    assert b2.arrays["obs"] is storage
    np.testing.assert_array_equal(b2.arrays["obs"], 2.0)
    q.release(b2)


def test_drop_oldest_when_full():
    q = TrajQueue(depth=2, register_gauge=False)
    for v in range(4):  # capacity 2: blocks 0 and 1 get recycled
        q.put(_block(float(v)), version=v)
    assert q.stats()["drops_full"] == 2
    got = [q.get(timeout=1.0), q.get(timeout=1.0)]
    assert [b.version for b in got] == [2, 3]  # newest survive, in order
    for b in got:
        q.release(b)


def test_staleness_drop_at_get():
    q = TrajQueue(depth=4, max_staleness=2, register_gauge=False)
    for v in range(3):
        q.put(_block(float(v)), version=v)
    q.set_consumer_version(4)  # lags: 4, 3, 2
    b = q.get(timeout=1.0)
    assert b is not None and b.version == 2  # 0 and 1 aged out
    assert q.stats()["drops_stale"] == 2
    assert q.stats()["observe_staleness"] == 2
    q.release(b)


def test_block_policy_put_waits_for_free_slot():
    q = TrajQueue(depth=1, policy="block", register_gauge=False)
    assert q.put(_block(0.0), version=0)
    assert not q.put(_block(1.0), version=1, timeout=0.05)  # full: timeout

    def consume():
        b = q.get(timeout=5.0)
        time.sleep(0.05)
        q.release(b)

    t = threading.Thread(target=consume)
    t.start()
    assert q.put(_block(1.0), version=1, timeout=5.0)  # slot freed mid-wait
    t.join()
    assert q.stats()["drops_full"] == 0


def test_gauge_rides_sampler_rows_until_close():
    q = TrajQueue(depth=2)
    try:
        q.put(_block(1.0), version=0)
        q.set_consumer_version(1)
        b = q.get(timeout=1.0)
        q.release(b)
        row = sampler.sample_row()
        gauge = next(
            (v for k, v in row.items() if k.startswith("traj_queue")), None
        )
        assert gauge is not None, row.keys()
        assert gauge["observe_staleness"] == 1
        assert gauge["puts"] == 1 and gauge["gets"] == 1
    finally:
        q.close()
    assert not any(
        k.startswith("traj_queue") for k in sampler.sample_row()
    )


def test_publisher_versioned_wait():
    pub = PolicyPublisher({"w": 0}, version=0)
    assert pub.wait_for(0, timeout=0.1)
    assert not pub.wait_for(2, timeout=0.05)
    stop = threading.Event()
    stop.set()
    assert not pub.wait_for(2, stop=stop)  # stop wins over the wait
    pub.publish({"w": 1}, version=2)
    assert pub.wait_for(2, timeout=0.1)
    version, params = pub.get()
    assert version == 2 and params == {"w": 1}


def test_merged_episode_tracker_report():
    from actor_critic_tpu.algos.host_loop import (
        EpisodeTracker,
        MergedEpisodeTracker,
    )

    a, b = EpisodeTracker(2), EpisodeTracker(2)
    a.finished.extend([10.0, 20.0])
    b.finished.extend([30.0])
    merged = MergedEpisodeTracker([a, b])
    rep = merged.report()
    assert rep["episodes"] == 3.0
    assert rep["recent_return"] == 20.0
    assert np.isnan(MergedEpisodeTracker([]).report()["recent_return"])


def test_run_report_renders_queue_row(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "run_report",
        Path(__file__).parent.parent / "scripts" / "run_report.py",
    )
    run_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(run_report)

    rows = [
        {"ts": 1.0, "recompiles": 0,
         "traj_queue": {"capacity": 4, "depth": 1, "puts": 3, "gets": 2,
                        "drops_full": 0, "drops_stale": 0,
                        "observe_staleness": 0, "staleness_max": 1,
                        "learner_idle_s": 0.1}},
        {"ts": 6.0, "recompiles": 0,
         "traj_queue": {"capacity": 4, "depth": 3, "puts": 30, "gets": 20,
                        "drops_full": 5, "drops_stale": 2,
                        "observe_staleness": 1, "staleness_max": 3,
                        "learner_idle_s": 0.4}},
    ]
    text = "\n".join(run_report.resource_summary(rows))
    assert "traj queue" in text
    assert "max 3 (capacity 4)" in text
    assert "5 full + 2 stale" in text
    assert "staleness last 1 / max 3" in text

    # And end to end through render(): the row must survive real files.
    (tmp_path / "resources.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in rows)
    )
    report = run_report.render(str(tmp_path))
    assert "traj queue" in report
