"""Tier-1 wiring for numsan (ISSUE 14 runtime half).

Mirrors test_racesan/test_fleetsan's layers: (1) the quick profile
sweeps clean, (2) a seed replays bit-identically, (3) every reverted-
guard mode is caught deterministically on every schedule, (4) the
tolerated poisons (denormal, large-but-finite) never fire a guard,
(5) the CLI's exit codes stay distinct.
"""

import importlib.util
from pathlib import Path

import numpy as np
import pytest

from actor_critic_tpu.analysis import numsan

REPO = Path(__file__).parent.parent


def _load_cli():
    spec = importlib.util.spec_from_file_location(
        "numsan_cli", REPO / "scripts" / "numsan.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# clean sweeps
# ---------------------------------------------------------------------------


def test_quick_profile_sweeps_clean():
    out = numsan.quick_profile(schedules=10, seed0=0)
    assert out["violations"] == 0
    assert out["schedules"] == 10
    # at least one publish/checkpoint-shaped guard fired across the
    # sweep (nonfinite poisons dominate the menus; the bf16-update
    # schedules drive the same sinks)
    fired = (
        out["publish"]["rejections"]
        + out["checkpoint"]["refusals"]
        + out["bf16_update"]["rejections"]
        + out["bf16_update"]["refusals"]
    )
    assert fired > 0


def test_update_poisons_fire_divergence_monitor():
    # seeds are cheap once the tiny program is compiled; sweep enough
    # rounds that the nonfinite poisons certainly appear
    out = numsan.exercise_sweep(
        range(0, 6), lambda s: numsan.exercise_update(s, rounds=2)
    )
    assert out["violations"] == 0
    assert out["divergence_events"] > 0


def test_bf16_update_poisons_refused_at_every_sink():
    """ISSUE 19: the bf16_compute update program's poisoned params must
    be refused by publish/mailbox/swap/checkpoint exactly like the fp32
    plane's — and the clean bf16 loss itself must be finite (the
    fp32-accumulator discipline)."""
    out = numsan.exercise_sweep(
        range(0, 4), lambda s: numsan.exercise_bf16_update(s)
    )
    assert out["violations"] == 0
    # nonfinite poisons dominate the menu: the rejection/refusal
    # counters must have fired across the sweep
    assert out["rejections"] + out["refusals"] > 0


def test_codec_saturations_observed():
    out = numsan.exercise_sweep(
        range(0, 8), lambda s: numsan.exercise_codec(s)
    )
    assert out["violations"] == 0
    assert out["saturations"] > 0


# ---------------------------------------------------------------------------
# bit-identical replay per seed
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "fn",
    [
        numsan.exercise_update,
        numsan.exercise_bf16_update,
        numsan.exercise_publish,
        numsan.exercise_checkpoint,
        numsan.exercise_codec,
    ],
)
def test_replay_is_bit_identical_per_seed(fn):
    a, b = fn(11), fn(11)
    assert a["trace"] == b["trace"]
    different = fn(12)
    # a different seed must be allowed to differ (no vacuous equality)
    assert (different["trace"] != a["trace"]) or (
        different.get("poison") != a.get("poison")
    )


# ---------------------------------------------------------------------------
# reverted-guard modes: caught deterministically on EVERY schedule
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_reverted_publish_guard_detected(seed):
    with pytest.raises(numsan.NumSanError, match="REVERTED GUARD"):
        numsan.exercise_publish(seed, revert=True)


@pytest.mark.parametrize("seed", [0, 1])
def test_reverted_checkpoint_guard_detected(seed):
    with pytest.raises(numsan.NumSanError, match="REVERTED GUARD"):
        numsan.exercise_checkpoint(seed, revert=True)


@pytest.mark.parametrize("seed", [0, 1])
def test_reverted_bf16_update_guard_detected(seed):
    with pytest.raises(numsan.NumSanError, match="REVERTED GUARD"):
        numsan.exercise_bf16_update(seed, revert=True)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_reverted_codec_wrap_detected(seed):
    with pytest.raises(numsan.NumSanError, match="REVERTED CODEC"):
        numsan.exercise_codec(seed, revert=True)


def test_revert_mode_restores_the_guard():
    """The guards-disabled context must restore check_finite even when
    the exerciser raises — a leaked no-op would silently disarm every
    production gate for the rest of the process."""
    from actor_critic_tpu.utils import numguard

    orig = numguard.check_finite
    with pytest.raises(numsan.NumSanError):
        numsan.exercise_publish(0, revert=True)
    assert numguard.check_finite is orig
    with pytest.raises(numguard.NonFiniteError):
        numguard.check_finite(
            {"w": np.array([np.nan], np.float32)}, "post-revert"
        )


# ---------------------------------------------------------------------------
# tolerance direction: denormals never fire a guard
# ---------------------------------------------------------------------------


def test_denormal_poisons_are_tolerated():
    # seeds chosen so the menu draw lands on "denormal"
    import random

    hits = 0
    for seed in range(40):
        if random.Random(seed).randrange(4) == 3:  # the denormal slot
            out = numsan.exercise_publish(seed)
            assert out["poison"] == "denormal"
            assert out["rejections"] == 0 and out["violations"] == 0
            hits += 1
            if hits >= 2:
                break
    assert hits >= 1, "no denormal seed in range — widen the sweep"


# ---------------------------------------------------------------------------
# CLI exit codes
# ---------------------------------------------------------------------------


def test_cli_exit_codes(capsys):
    cli = _load_cli()
    assert cli.main(["--scenario", "codec", "--schedules", "4"]) == 0
    assert cli.main(
        ["--scenario", "codec", "--revert", "--schedules", "2"]
    ) == 1
    assert cli.main(
        ["--scenario", "publish", "--revert", "--schedules", "2"]
    ) == 1
    assert cli.main(
        ["--scenario", "bf16-update", "--revert", "--schedules", "2"]
    ) == 1
    # --revert without a gated scenario is a usage crash, not a clean run
    assert cli.main(["--revert"]) == 2
    capsys.readouterr()


def test_cli_json_mode(capsys):
    import json

    cli = _load_cli()
    rc = cli.main(
        ["--scenario", "publish", "--schedules", "3", "--json"]
    )
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["schedules"] == 3
    assert payload["violations"] == 0
