"""Compile-once subsystem (utils/compile_cache.py, ISSUE 4): persistent
cache warm/cold behavior, AOT warmup signature-exactness (the loop's own
first dispatch must HIT what warmup compiled), shape-stabilized chunking
(two programs total, bit-compatible semantics), and the steady-state
compile-count regression contract: after warmup + first dispatch, zero
recompiles."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from actor_critic_tpu.telemetry import profiler
from actor_critic_tpu.utils import compile_cache


from conftest import new_compile_records as _new_records


def _require_introspection():
    if not profiler.ensure_compile_introspection():
        pytest.skip("jax compile funnel unavailable in this jax version")


# ---------------------------------------------------------------- utilities

def test_bucket_size_and_pad_to_bucket():
    assert compile_cache.bucket_size(5, (4, 8, 16)) == 8
    assert compile_cache.bucket_size(8, (4, 8, 16)) == 8
    assert compile_cache.bucket_size(0, (4,)) == 4
    with pytest.raises(ValueError):
        compile_cache.bucket_size(17, (4, 8, 16))
    with pytest.raises(ValueError):
        compile_cache.bucket_size(-1, (4,))

    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    padded, mask = compile_cache.pad_to_bucket(x, (4, 8))
    assert padded.shape == (8, 2) and mask.shape == (8,)
    np.testing.assert_array_equal(padded[:6], x)
    np.testing.assert_array_equal(padded[6:], 0.0)
    np.testing.assert_array_equal(mask, [1, 1, 1, 1, 1, 1, 0, 0])
    # Exact fit: no copy semantics promised, but shape/mask must be right.
    same, mask = compile_cache.pad_to_bucket(x, (6,))
    assert same.shape == (6, 2) and mask.sum() == 6


def test_resolve_cache_dir_policy(tmp_path):
    resolve = compile_cache.resolve_cache_dir
    ck = str(tmp_path / "ck")
    assert resolve("auto", ck).endswith("xla_cache")
    assert resolve("auto", None) is None
    assert resolve(None, ck).endswith("xla_cache")
    assert resolve("none", ck) is None
    assert resolve("off", ck) is None
    assert resolve("", ck) is None
    assert resolve("/x/y", ck) == "/x/y"


# ------------------------------------------------------- persistent cache

def test_persistent_cache_cold_then_warm(tmp_path):
    """Cold compile writes the cache (miss counted); after clearing the
    in-memory jit caches, the same program deserializes (hit counted) —
    the cross-leg mechanism `run_resumable.sh` relies on."""
    import os

    with compile_cache.temporary_cache(tmp_path / "cc") as cc_dir:
        stats0 = compile_cache.cache_stats()

        def fn(x):
            return jnp.tanh(x @ x.T).sum() + x.sum()

        x = jnp.ones((97, 53))  # unlikely-collision shape for this process
        jax.block_until_ready(jax.jit(fn)(x))
        stats1 = compile_cache.cache_stats()
        assert stats1["misses"] > stats0["misses"]
        assert any(f.endswith("-cache") for f in os.listdir(cc_dir))

        jax.clear_caches()  # "new process": in-memory jit caches gone
        jax.block_until_ready(jax.jit(fn)(x))
        stats2 = compile_cache.cache_stats()
        assert stats2["hits"] > stats1["hits"]


# --------------------------------------------------- shape-stable chunking

def _tiny_a2c():
    from actor_critic_tpu.algos import a2c
    from actor_critic_tpu.envs import make_two_state_mdp

    env = make_two_state_mdp()
    cfg = a2c.A2CConfig(num_envs=8, rollout_steps=4, hidden=(16,))
    return a2c, env, cfg


def test_chunked_step_masked_tail_matches_per_iteration():
    """The n_valid-masked bucket must advance exactly k iterations —
    same trajectory as k per-iteration dispatches from the same state —
    and report the LAST VALID iteration's metrics."""
    a2c, env, cfg = _tiny_a2c()
    raw = a2c.make_train_step(env, cfg)
    step = compile_cache.make_chunked_step(raw, 4)

    sA, _ = step(a2c.init_state(env, cfg, jax.random.key(0)), 4)
    sA, mA = step(sA, 3)  # masked: 3 valid of 4 slots

    sB, _ = step(a2c.init_state(env, cfg, jax.random.key(0)), 4)
    per_iter = jax.jit(raw)
    for _ in range(3):
        sB, mB = per_iter(sB)

    for a, b in zip(jax.tree.leaves(sA), jax.tree.leaves(sB)):
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        )
    for k in mB:
        np.testing.assert_allclose(
            np.asarray(mA[k]), np.asarray(mB[k]), rtol=1e-4, atol=1e-6
        )


def test_chunked_step_compiles_exactly_two_programs():
    """Every partial k shares ONE masked program (the PR 3 attribution
    table's top recompile source was a fresh program per distinct static
    tail k)."""
    _require_introspection()
    a2c, env, cfg = _tiny_a2c()
    step = compile_cache.make_chunked_step(a2c.make_train_step(env, cfg), 4)
    state = a2c.init_state(env, cfg, jax.random.key(1))

    n0 = profiler.compile_event_count()
    state, _ = step(state, 4)   # full program
    state, _ = step(state, 3)   # masked program
    mid = profiler.compile_event_count()
    state, _ = step(state, 1)   # masked REUSED
    state, _ = step(state, 2)   # masked REUSED
    state, _ = step(state, 4)   # full REUSED
    assert profiler.compile_event_count() == mid, [
        r["name"] for r in _new_records(n0)
    ]
    names = [r["name"] for r in _new_records(n0)]
    assert names.count("jit_full") == 1 and names.count("jit_masked") == 1


# ------------------------------------------------------------- AOT warmup

def test_warmup_runner_contains_thunk_errors():
    ok = []
    runner = compile_cache.WarmupRunner(
        [("boom", lambda: 1 / 0), ("fine", lambda: ok.append(1))]
    ).start()
    assert runner.wait(30)
    assert "error" in runner.results[0]
    assert ok and "compile_s" in runner.results[1]


def test_fused_warmup_makes_first_dispatch_a_cache_hit(tmp_path):
    """The warmup thread AOT-compiles the chunked programs from ABSTRACT
    state; the loop's own jit objects must then funnel through as
    persistent-cache HITS — i.e. each entry point really compiles once
    (0 recompiles after warmup)."""
    _require_introspection()
    a2c, env, cfg = _tiny_a2c()
    with compile_cache.temporary_cache(tmp_path / "cc"):
        ctx = compile_cache.WarmupContext(
            algo="a2c", fused=True, spec=env.spec, cfg=cfg, env=env,
            chunk=3, iterations=7, eval_every=0,
        )
        plan = compile_cache.plan_warmup(ctx)
        assert [n for n, _ in plan] == ["a2c.make_train_step"]
        n0 = profiler.compile_event_count()
        runner = compile_cache.WarmupRunner(plan).start()
        assert runner.wait(300) and "error" not in runner.results[0], (
            runner.results
        )

        # The "live" loop builds its OWN step (fresh jit objects, same
        # HLO) — exactly what train.py's run_fused does.
        step = compile_cache.make_chunked_step(
            a2c.make_train_step(env, cfg), 3
        )
        state = a2c.init_state(env, cfg, jax.random.key(0))
        from actor_critic_tpu.utils.checkpoint import checkpointed_train

        state, _ = checkpointed_train(step, state, 7, stride=3)

    records = _new_records(n0)
    for name in ("jit_full", "jit_masked"):
        evs = [r for r in records if r["name"] == name]
        real = [r for r in evs if not r.get("cache_hit")]
        hits = [r for r in evs if r.get("cache_hit")]
        assert len(real) == 1, (name, evs)   # warmup's one true compile
        assert hits, (name, evs)             # the loop hit the cache


def test_mixture_fleet_one_program_zero_steady_state_recompiles(tmp_path):
    """ISSUE 11 acceptance: a heterogeneous mixture fleet of THREE env
    types (CartPole + Pendulum + Acrobot behind the padded shared
    interface) steps inside ONE fused XLA program — the registered
    planners AOT-compile the train step and the per-type eval, the live
    loop's first dispatches are persistent-cache hits, and steady state
    (more train iterations + typed evals across EVERY type) compiles
    NOTHING: the per-instance `lax.switch` and the traced type-id eval
    keep the whole universe on a fixed program set."""
    _require_introspection()
    from actor_critic_tpu.algos import a2c
    from actor_critic_tpu.envs import make_mixture
    from actor_critic_tpu.envs import mixture as mx

    env = make_mixture("cartpole,pendulum,acrobot", randomize=0.2)
    cfg = a2c.A2CConfig(num_envs=8, rollout_steps=2, hidden=(8,))
    with compile_cache.temporary_cache(tmp_path / "cc"):
        ctx = compile_cache.WarmupContext(
            algo="a2c", fused=True, spec=env.spec, cfg=cfg, env=env,
            eval_every=2,
        )
        plan = compile_cache.plan_warmup(ctx)
        assert [n for n, _ in plan] == [
            "a2c.make_eval_fn", "a2c.make_train_step",
            "mixture.make_typed_eval",
        ]
        n0 = profiler.compile_event_count()
        runner = compile_cache.WarmupRunner(plan).start()
        assert runner.wait(600), runner.results
        assert not [r for r in runner.results if "error" in r], runner.results

        # The live loop's own jit objects (fresh, same HLO), exactly as
        # train.py's run_fused builds them.
        step = jax.jit(a2c.make_train_step(env, cfg), donate_argnums=0)
        ev = jax.jit(a2c.make_eval_fn(env, cfg), static_argnums=(2, 3))
        typed = jax.jit(
            mx.make_typed_eval(env, a2c.make_network(env, cfg)),
            static_argnums=(3, 4),
        )
        state = a2c.init_state(env, cfg, jax.random.key(0))
        key = jax.random.key(1)
        state, _ = step(state)
        float(ev(state, key))
        for t in range(env.n_types):
            float(typed(state, key, jnp.asarray(t, jnp.int32)))
        c0 = profiler.compile_event_count()
        # Steady state: more iterations, the aggregate eval, and the
        # typed eval across every member type — zero compile events.
        for _ in range(3):
            state, _ = step(state)
        float(ev(state, key))
        for t in range(env.n_types):
            float(typed(state, key, jnp.asarray(t, jnp.int32)))
        steady = profiler.compile_event_count() - c0
        assert steady == 0, [
            r["name"] for r in profiler.compile_records()[-steady:]
        ]

    # Warmup's one true compile of the mixture train step (the ONE
    # program the whole heterogeneous fleet steps in); the live loop's
    # dispatch funneled through as a persistent-cache hit.
    records = _new_records(n0)
    step_evs = [r for r in records if "train_step" in r["name"]]
    real = [r for r in step_evs if not r.get("cache_hit")]
    assert len(real) == 1, [
        (r["name"], r.get("cache_hit")) for r in step_evs
    ]
    assert any(r.get("cache_hit") for r in step_evs), step_evs


def test_host_ppo_steady_state_zero_recompiles(tmp_path):
    """ISSUE 4 acceptance: a short host loop under the compile listener —
    every registered entry point compiles exactly once (warmup), the
    loop's first dispatch is a cache hit, and steady state (iterations
    past the second) triggers ZERO further compile events."""
    pytest.importorskip("gymnasium")
    _require_introspection()
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.envs.host_pool import HostEnvPool

    cfg = ppo.PPOConfig(
        num_envs=4, rollout_steps=8, epochs=1, num_minibatches=2,
        hidden=(16,),
    )
    pool = HostEnvPool("CartPole-v1", num_envs=4, seed=0)
    try:
        with compile_cache.temporary_cache(tmp_path / "cc"):
            ctx = compile_cache.WarmupContext(
                algo="ppo", fused=False, spec=pool.spec, cfg=cfg,
                eval_every=0, overlap=True,
            )
            plan = compile_cache.plan_warmup(ctx)
            # CartPole's MLP mirrors acting/eval on the host, so the only
            # device entry point this run dispatches is the update.
            assert [n for n, _ in plan] == ["ppo.make_host_update_step"]
            n0 = profiler.compile_event_count()
            runner = compile_cache.WarmupRunner(plan).start()
            assert runner.wait(300) and "error" not in runner.results[0], (
                runner.results
            )

            counts = {}

            def log_fn(it, m):
                counts[it] = profiler.compile_event_count()

            ppo.train_host(
                pool, cfg, num_iterations=4, log_every=1, log_fn=log_fn,
            )
    finally:
        pool.close()

    records = _new_records(n0)
    update_evs = [r for r in records if r["name"] == "jit_update"]
    real = [r for r in update_evs if not r.get("cache_hit")]
    assert len(real) == 1, update_evs   # warmup compiled it exactly once
    assert any(r.get("cache_hit") for r in update_evs), update_evs
    # Steady state: whatever one-time micro-jits iteration 1/2 paid
    # (PRNG split etc.), iterations 3..4 must compile NOTHING.
    assert counts[4] == counts[2], records


def test_quantized_ingest_warmup_steady_state_zero_recompiles(tmp_path):
    """ISSUE 8: the QUANTIZED off-policy ingest+update path keeps the
    compile-once contract — the registered `ddpg.make_host_ingest_update`
    planner derives the abstract learner tree WITH QuantStats leaves
    (replay_dtype rides the config), warmup's one true compile makes the
    live loop's first dispatch a persistent-cache hit, and repeat
    dispatches compile nothing."""
    _require_introspection()
    import jax.numpy as jnp

    from actor_critic_tpu.algos import ddpg
    from actor_critic_tpu.algos.common import OffPolicyTransition
    from actor_critic_tpu.envs.jax_env import EnvSpec

    cfg = ddpg.DDPGConfig(
        num_envs=2, steps_per_iter=4, updates_per_iter=1,
        buffer_capacity=256, batch_size=8, warmup_steps=0, hidden=(16,),
        replay_dtype="mixed",
    )
    spec = EnvSpec(obs_shape=(3,), action_dim=1, discrete=False)
    with compile_cache.temporary_cache(tmp_path / "cc"):
        ctx = compile_cache.WarmupContext(
            algo="ddpg", fused=False, spec=spec, cfg=cfg,
            eval_every=0, overlap=False,
        )
        plan = compile_cache.plan_warmup(ctx)
        ingest_entries = [
            n for n, _ in plan if n == "ddpg.make_host_ingest_update"
        ]
        assert ingest_entries, [n for n, _ in plan]
        n0 = profiler.compile_event_count()
        runner = compile_cache.WarmupRunner(
            [e for e in plan if e[0] == "ddpg.make_host_ingest_update"]
        ).start()
        assert runner.wait(300) and "error" not in runner.results[0], (
            runner.results
        )

        # The live loop's own jit objects (fresh trace, same HLO).
        ingest = ddpg.make_host_ingest_update(1, cfg)
        learner = ddpg.init_learner((3,), 1, cfg, jax.random.key(0))
        assert learner.replay.storage.obs.dtype == jnp.int8
        K, E = cfg.steps_per_iter, cfg.num_envs

        def block(seed):
            r = np.random.default_rng(seed)
            return OffPolicyTransition(
                obs=jnp.asarray(r.normal(size=(K, E, 3)), jnp.float32),
                action=jnp.asarray(r.uniform(-1, 1, (K, E, 1)), jnp.float32),
                reward=jnp.asarray(r.normal(size=(K, E)), jnp.float32),
                next_obs=jnp.asarray(r.normal(size=(K, E, 3)), jnp.float32),
                terminated=jnp.zeros((K, E), jnp.float32),
                done=jnp.zeros((K, E), jnp.float32),
            )

        counts = []
        for it in range(4):
            learner, _ = ingest(
                learner, block(it), jnp.asarray(64, jnp.int32)
            )
            jax.block_until_ready(learner.replay.quant)
            counts.append(profiler.compile_event_count())

    records = _new_records(n0)
    evs = [r for r in records if r["name"] == "jit_ingest_update"]
    real = [r for r in evs if not r.get("cache_hit")]
    assert len(real) == 1, evs          # warmup's one true compile
    assert any(r.get("cache_hit") for r in evs), evs  # live loop hit it
    # Steady state: iterations past the first compile NOTHING.
    assert counts[-1] == counts[1], records


def test_fused_device_update_steady_state_zero_recompiles(tmp_path):
    """ISSUE 19: the FUSED device-plane consume (ring gather + codec
    decode + the `common.gae_targets` advantage seam + update, one
    program under correction='none') keeps the compile-once contract —
    the registered `ppo.make_device_update_step` planner derives the
    abstract ring state, warmup's one true compile makes the live
    loop's first dispatch a persistent-cache hit, and consuming more
    blocks compiles NOTHING."""
    _require_introspection()
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.data_plane import ring as dp_ring
    from actor_critic_tpu.envs.jax_env import EnvSpec

    spec = EnvSpec(
        obs_shape=(4,), action_dim=2, discrete=True,
        obs_dtype=np.float32, can_truncate=True,
    )
    cfg = ppo.PPOConfig(
        num_envs=4, rollout_steps=8, epochs=1, num_minibatches=1,
        hidden=(16,),
    )
    with compile_cache.temporary_cache(tmp_path / "cc"):
        ctx = compile_cache.WarmupContext(
            algo="ppo", fused=False, spec=spec, cfg=cfg,
            eval_every=0, overlap=True, async_actors=1,
            async_correction="none", data_plane="device",
            plane_codec="fp32", queue_depth=2,
        )
        plan = compile_cache.plan_warmup(ctx)
        fused_entries = [
            e for e in plan if e[0] == "ppo.make_device_update_step"
        ]
        assert fused_entries, [n for n, _ in plan]
        n0 = profiler.compile_event_count()
        runner = compile_cache.WarmupRunner(fused_entries).start()
        assert runner.wait(300) and "error" not in runner.results[0], (
            runner.results
        )

        # The live loop's own jit object (fresh trace, same HLO).
        block_spec = ppo.async_block_spec(spec, cfg, 1, "none")
        ring = dp_ring.DeviceTrajRing(
            depth=2, block_spec=block_spec, codec="fp32",
            register_gauge=False,
        )
        try:
            update = ppo.make_device_update_step(
                spec, cfg, ring.codecs, correction="none"
            )
            key = jax.random.key(0)
            params, opt_state = ppo.init_host_params(spec, cfg, key)
            T, E = cfg.rollout_steps, cfg.num_envs

            def block_for(i):
                rng = np.random.default_rng(i)
                obs = rng.normal(size=(T, E, 4)).astype(np.float32)
                return {
                    "obs": obs,
                    "action": rng.integers(0, 2, (T, E)),
                    "log_prob": (
                        rng.normal(size=(T, E)) * 0.1 - 0.69
                    ).astype(np.float32),
                    "value": rng.normal(size=(T, E)).astype(np.float32),
                    "reward": np.ones((T, E), np.float32),
                    "done": np.zeros((T, E), np.float32),
                    "terminated": np.zeros((T, E), np.float32),
                    "final_obs": obs.copy(),
                    "last_obs": rng.normal(size=(E, 4)).astype(
                        np.float32
                    ),
                    "final_values": rng.normal(size=(T, E)).astype(
                        np.float32
                    ),
                    "bootstrap_value": rng.normal(size=(E,)).astype(
                        np.float32
                    ),
                }

            counts = []
            for i in range(4):
                ring.put(block_for(i), version=i)
                lease = ring.get(timeout=5.0)
                slot_dev = jax.device_put(np.int32(lease.slot))
                out = ring.run(
                    lambda s: update(params, opt_state, s, slot_dev, key)
                )
                jax.block_until_ready(out)
                ring.release(lease)
                counts.append(profiler.compile_event_count())
        finally:
            ring.close()

    records = _new_records(n0)
    evs = [r for r in records if r["name"] == "jit_device_update"]
    real = [r for r in evs if not r.get("cache_hit")]
    assert len(real) == 1, evs          # warmup's one true compile
    assert any(r.get("cache_hit") for r in evs), evs  # live loop hit it
    # Steady state: blocks past the first compile NOTHING.
    assert counts[-1] == counts[0], records


def test_restore_normalizes_for_compile_cache(tmp_path):
    """A restored state must (a) carry UNCOMMITTED, XLA-owned leaves —
    orbax's committed arrays lower byte-different HLO (per-arg
    mhlo.sharding attrs) that misses every cache entry a fresh process
    wrote, and donating restore-aliased buffers into deserialized
    executables corrupts the heap — and (b) therefore lower EXACTLY the
    fresh process's module, so resumed legs hit the fresh leg's cache."""
    from actor_critic_tpu.utils.checkpoint import Checkpointer

    a2c, env, cfg = _tiny_a2c()
    state = a2c.init_state(env, cfg, jax.random.key(0))
    with Checkpointer(tmp_path / "ck") as ck:
        ck.save(1, state, force=True)
        ck.wait()
        # Normalization is gated on a live cache (its 2x transient
        # device materialization must not tax cache-less restores of
        # replay-ring-sized states).
        with compile_cache.temporary_cache(tmp_path / "cc"):
            restored = ck.restore(state, 1)
    for leaf in jax.tree.leaves(restored):
        assert not leaf.committed
    step = compile_cache.make_chunked_step(a2c.make_train_step(env, cfg), 2)
    fresh_hlo = step.full.lower(state).as_text()
    restored_hlo = step.full.lower(restored).as_text()
    assert fresh_hlo == restored_hlo
    # And the restored values round-tripped exactly despite the clone.
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------- telemetry

def test_exporter_reports_compile_cache_counters(tmp_path):
    from actor_critic_tpu.telemetry.exporter import render_metrics
    from actor_critic_tpu.telemetry.session import TelemetrySession

    s = TelemetrySession(
        tmp_path / "t", sample_resources=False, profile=False
    )
    try:
        text = render_metrics(s)
    finally:
        s.close()
    assert "actor_critic_compile_cache_hits_total" in text
    assert "actor_critic_compile_cache_misses_total" in text
    assert "actor_critic_compile_cache_enabled" in text


def test_run_report_cache_hit_attribution(tmp_path):
    import importlib.util
    import json
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "run_report",
        Path(__file__).parent.parent / "scripts" / "run_report.py",
    )
    run_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(run_report)

    (tmp_path / "events.jsonl").write_text(
        "".join(
            json.dumps(r) + "\n"
            for r in [
                {"ts": 1.0, "kind": "session_start"},
                {"ts": 2.0, "kind": "compile", "name": "jit_update",
                 "compile_s": 2.0},
                {"ts": 3.0, "kind": "compile", "name": "jit_update",
                 "compile_s": 0.02, "cache_hit": True},
            ]
        )
    )
    report = run_report.render(str(tmp_path))
    assert "| `jit_update` | 2 | 1 | 2.02s" in report, report
    assert "persistent-cache hit(s)" in report


# ------------------------------------------------------- serving (ISSUE 10)

def test_serving_context_plans_only_serving_planners():
    """plan_warmup runs exactly one registry side per context: a
    serving context (serving_buckets non-empty) plans ONLY the serving
    act-bucket planner — never the training update/eval programs a
    gateway process would waste startup compiling — and a training
    context never plans the serving side."""
    from actor_critic_tpu.envs import make_cartpole
    from actor_critic_tpu.algos import ppo
    import actor_critic_tpu.serving  # noqa: F401 — planner registration

    spec = make_cartpole().spec
    cfg = ppo.PPOConfig(hidden=(8,))
    serve_ctx = compile_cache.WarmupContext(
        algo="ppo", fused=False, spec=spec, cfg=cfg,
        serving_buckets=(1, 4),
    )
    names = [n for n, _ in compile_cache.plan_warmup(serve_ctx)]
    assert names == ["engine.make_act_program"]
    train_ctx = compile_cache.WarmupContext(
        algo="ppo", fused=False, spec=spec, cfg=cfg, eval_every=0,
    )
    assert "engine.make_act_program" not in [
        n for n, _ in compile_cache.plan_warmup(train_ctx)
    ]


def test_serving_steady_state_zero_recompiles(tmp_path):
    """ISSUE 10 acceptance: after the serving warmup planner AOT-
    compiles every act bucket and the engine's concrete warm pass hits
    those cache entries, steady-state serving — requests across EVERY
    bucket size, through the micro-batcher, across a hot-swap — emits
    ZERO further compile-funnel events (not even deserializations)."""
    _require_introspection()
    import numpy as np

    from actor_critic_tpu import serving
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.envs import make_cartpole

    spec = make_cartpole().spec
    cfg = ppo.PPOConfig(hidden=(8, 8))
    buckets = (1, 2, 4, 8)
    with compile_cache.temporary_cache(tmp_path / "cc"):
        ctx = compile_cache.WarmupContext(
            algo="ppo", fused=False, spec=spec, cfg=cfg,
            serving_buckets=buckets,
        )
        plan = compile_cache.plan_warmup(ctx)
        # Count via the MONOTONIC event counter, not ring indices: in a
        # full-suite run the 256-entry record ring is already at
        # capacity, so records[n0:] silently misses new entries.
        c0 = profiler.compile_event_count()
        runner = compile_cache.WarmupRunner(plan).start()
        assert runner.wait(300) and "error" not in runner.results[0], (
            runner.results
        )
        engine = serving.PolicyEngine(
            spec, cfg, algo="ppo", buckets=buckets
        )
        store = serving.PolicyStore()
        store.register(
            "default", engine, serving.init_params(spec, cfg, "ppo", 0)
        )
        engine.warm(store.get().params)
        delta = profiler.compile_event_count() - c0
        warm_records = (
            profiler.compile_records()[-delta:] if delta else []
        )
        act_real = [
            r for r in warm_records
            if "act" in r["name"] and not r.get("cache_hit")
        ]
        # The planner's one true compile per bucket; the engine's warm
        # re-traces deserialize those entries (cache hits).
        assert len(act_real) == len(buckets), warm_records

        c1 = profiler.compile_event_count()
        batcher = serving.MicroBatcher(store, max_wait_us=0.0)
        try:
            for i, rows in enumerate((1, 2, 3, 4, 5, 6, 7, 8)):
                req = batcher.submit(
                    np.zeros((rows, *spec.obs_shape), np.float32)
                )
                batcher.wait(req, timeout=30)
                if i == 3:
                    # Hot-swap mid-stream: the uncommitted-restore
                    # install path must not change the lowered HLO.
                    store.swap(
                        "default",
                        serving.init_params(spec, cfg, "ppo", 1),
                    )
        finally:
            batcher.close()
        steady = profiler.compile_event_count() - c1
        assert steady == 0, (  # 0 recompiles after warmup
            steady, profiler.compile_records()[-steady:]
        )
