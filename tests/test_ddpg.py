"""DDPG/TD3 tests: update mechanics (warmup gating, policy delay, twin-Q
targets) + learning on the analytic point-mass env (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np

from actor_critic_tpu import replay
from actor_critic_tpu.algos import ddpg
from actor_critic_tpu.algos.common import OffPolicyTransition
from actor_critic_tpu.envs import make_point_mass


def _small_cfg(**kw):
    base = dict(
        num_envs=16,
        steps_per_iter=4,
        updates_per_iter=2,
        buffer_capacity=4096,
        batch_size=64,
        hidden=(32, 32),
        actor_lr=1e-3,
        critic_lr=1e-3,
        warmup_steps=128,
    )
    base.update(kw)
    return ddpg.DDPGConfig(**base)


def _filled_learner(cfg, key=0, n_items=512, obs_dim=1, act_dim=1):
    """Learner whose ring already holds random transitions."""
    k = jax.random.key(key)
    k, lk, dk = jax.random.split(k, 3)
    learner = ddpg.init_learner((obs_dim,), act_dim, cfg, lk)
    ks = jax.random.split(dk, 4)
    batch = OffPolicyTransition(
        obs=jax.random.normal(ks[0], (n_items, obs_dim)),
        action=jax.random.uniform(ks[1], (n_items, act_dim), minval=-1, maxval=1),
        reward=jax.random.normal(ks[2], (n_items,)),
        next_obs=jax.random.normal(ks[3], (n_items, obs_dim)),
        terminated=jnp.zeros((n_items,)),
        done=jnp.zeros((n_items,)),
    )
    return learner._replace(replay=replay.add_batch(learner.replay, batch))


def _greedy_eval(env, cfg, state) -> float:
    from actor_critic_tpu.algos.common import evaluate

    actor, _ = ddpg._modules(env.spec.action_dim, cfg)
    ret = evaluate(
        env, actor.apply, state.learner.actor_params, jax.random.key(99),
        num_envs=32, num_steps=16,
    )
    return float(ret)


def _params_equal(a, b):
    return all(
        bool(jnp.all(x == y)) for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


class TestUpdateMechanics:
    def test_warmup_blocks_learning(self):
        cfg = _small_cfg(updates_per_iter=1)
        learner = _filled_learner(cfg)
        loop = ddpg.make_update_loop(1, cfg)
        new, _ = loop(learner, jnp.asarray(False))
        assert _params_equal(new.actor_params, learner.actor_params)
        assert _params_equal(new.critic_params, learner.critic_params)
        assert int(new.update_count) == 0

    def test_update_changes_params(self):
        cfg = _small_cfg(updates_per_iter=1)
        learner = _filled_learner(cfg)
        loop = ddpg.make_update_loop(1, cfg)
        new, metrics = loop(learner, jnp.asarray(True))
        assert not _params_equal(new.critic_params, learner.critic_params)
        assert not _params_equal(new.actor_params, learner.actor_params)
        assert int(new.update_count) == 1
        assert np.isfinite(float(metrics["critic_loss"]))

    def test_policy_delay(self):
        """With delay=2, updates 0,2,... touch the actor; 1,3,... don't."""
        cfg = _small_cfg(updates_per_iter=1, twin_q=True, policy_delay=2)
        learner = _filled_learner(cfg)
        loop = jax.jit(ddpg.make_update_loop(1, cfg))
        s1, _ = loop(learner, jnp.asarray(True))  # count 0 → actor moves
        assert not _params_equal(s1.actor_params, learner.actor_params)
        s2, _ = loop(s1, jnp.asarray(True))  # count 1 → actor frozen
        assert _params_equal(s2.actor_params, s1.actor_params)
        assert _params_equal(s2.target_actor, s1.target_actor)
        s3, _ = loop(s2, jnp.asarray(True))  # count 2 → actor moves again
        assert not _params_equal(s3.actor_params, s2.actor_params)

    def test_target_nets_polyak_not_copy(self):
        cfg = _small_cfg(updates_per_iter=1, tau=0.005)
        learner = _filled_learner(cfg)
        new, _ = ddpg.make_update_loop(1, cfg)(learner, jnp.asarray(True))
        # targets moved but only slightly (τ-weighted), not a hard copy
        assert not _params_equal(new.target_critic, learner.target_critic)
        assert not _params_equal(new.target_critic, new.critic_params)

    def test_twin_q_shapes(self):
        cfg = _small_cfg(twin_q=True)
        _, critic = ddpg._modules(2, cfg)
        params = critic.init(jax.random.key(0), jnp.zeros((3, 4)), jnp.zeros((3, 2)))
        q1, q2 = critic.apply(params, jnp.zeros((3, 4)), jnp.zeros((3, 2)))
        assert q1.shape == q2.shape == (3,)


class TestFusedTrainer:
    def test_smoke_and_accounting(self):
        env = make_point_mass()
        cfg = _small_cfg()
        state, metrics = ddpg.train(env, cfg, num_iterations=3, seed=0)
        assert int(state.update_step) == 3
        assert int(state.env_steps) == 3 * cfg.steps_per_iter * cfg.num_envs
        for v in metrics.values():
            assert np.isfinite(float(v))

    def test_warmup_random_actions_fill_replay(self):
        env = make_point_mass()
        cfg = _small_cfg(warmup_steps=10_000)
        state, _ = ddpg.train(env, cfg, num_iterations=2, seed=0)
        assert int(state.learner.replay.size) == 2 * cfg.steps_per_iter * cfg.num_envs
        assert int(state.learner.update_count) == 0  # still warming up

    def test_ddpg_learns_point_mass(self):
        env = make_point_mass()
        cfg = _small_cfg(
            updates_per_iter=4, exploration_noise=0.2, warmup_steps=256,
            buffer_capacity=32768,  # hold the whole run: stale-regime-free
        )
        state, _ = ddpg.train(env, cfg, num_iterations=250, seed=1)
        # Optimal per-episode return is 0; random policy averages ≈ −6.
        ret = _greedy_eval(env, cfg, state)
        assert ret > -1.0, ret

    def test_td3_learns_point_mass(self):
        env = make_point_mass()
        cfg = ddpg.td3_config(
            num_envs=16, steps_per_iter=4, updates_per_iter=4,
            buffer_capacity=32768, batch_size=64, hidden=(32, 32),
            actor_lr=1e-3, critic_lr=1e-3, warmup_steps=256,
            exploration_noise=0.2,
        )
        state, _ = ddpg.train(env, cfg, num_iterations=250, seed=2)
        ret = _greedy_eval(env, cfg, state)
        assert ret > -1.0, ret


class TestHostPath:
    def test_host_ingest_update(self):
        """Host-block ingest inserts [K,E] transitions and updates."""
        cfg = _small_cfg(updates_per_iter=1, warmup_steps=0, batch_size=32)
        learner = ddpg.init_learner((3,), 2, cfg, jax.random.key(0))
        ingest = ddpg.make_host_ingest_update(2, cfg)
        K, E = 4, 8
        k = jax.random.key(1)
        traj = OffPolicyTransition(
            obs=jax.random.normal(k, (K, E, 3)),
            action=jnp.zeros((K, E, 2)),
            reward=jnp.ones((K, E)),
            next_obs=jax.random.normal(k, (K, E, 3)),
            terminated=jnp.zeros((K, E)),
            done=jnp.zeros((K, E)),
        )
        learner, metrics = ingest(learner, traj, jnp.asarray(K * E, jnp.int32))
        assert int(learner.replay.size) == K * E
        assert int(learner.update_count) == 1
        assert np.isfinite(float(metrics["critic_loss"]))


class TestNStep:
    """DDPGConfig.nstep — the replay.sample_sequences consumer."""

    def _seq(self, rewards, done, terminated, n):
        """Hand-built [1, n] window with distinguishable obs per step."""
        r = jnp.asarray([rewards], jnp.float32)
        return OffPolicyTransition(
            obs=jnp.arange(n, dtype=jnp.float32).reshape(1, n, 1),
            action=jnp.full((1, n, 1), 0.5),
            reward=r,
            next_obs=(10.0 + jnp.arange(n, dtype=jnp.float32)).reshape(1, n, 1),
            terminated=jnp.asarray([terminated], jnp.float32),
            done=jnp.asarray([done], jnp.float32),
        )

    def test_nstep_batch_no_done(self):
        g = 0.9
        seq = self._seq([1.0, 2.0, 4.0], [0, 0, 0], [0, 0, 0], 3)
        batch, boot = ddpg.nstep_batch(seq, g)
        assert np.isclose(float(batch.reward[0]), 1.0 + g * 2.0 + g * g * 4.0)
        assert float(batch.next_obs[0, 0]) == 12.0  # window end = last step
        assert float(batch.terminated[0]) == 0.0
        assert np.isclose(float(boot[0]), g**3)
        assert float(batch.obs[0, 0]) == 0.0 and float(batch.action[0, 0]) == 0.5

    def test_nstep_batch_terminates_mid_window(self):
        g = 0.9
        # done+terminated at k=1: G = r0 + g*r1, later rewards masked,
        # bootstrap discount g^2 but terminated=1 kills the bootstrap.
        seq = self._seq([1.0, 2.0, 100.0], [0, 1, 0], [0, 1, 0], 3)
        batch, boot = ddpg.nstep_batch(seq, g)
        assert np.isclose(float(batch.reward[0]), 1.0 + g * 2.0)
        assert float(batch.next_obs[0, 0]) == 11.0  # the done step's
        assert float(batch.terminated[0]) == 1.0
        assert np.isclose(float(boot[0]), g**2)

    def test_nstep_batch_truncates_first_step(self):
        g = 0.9
        # done (time-limit) at k=0 without termination: G = r0 only and the
        # bootstrap goes THROUGH next_obs_0 at discount g — identical to
        # the 1-step path for that transition.
        seq = self._seq([3.0, 7.0, 7.0], [1, 0, 0], [0, 0, 0], 3)
        batch, boot = ddpg.nstep_batch(seq, g)
        assert np.isclose(float(batch.reward[0]), 3.0)
        assert float(batch.next_obs[0, 0]) == 10.0
        assert float(batch.terminated[0]) == 0.0
        assert np.isclose(float(boot[0]), g)

    def test_nstep_requires_single_env(self):
        import pytest

        with pytest.raises(ValueError, match="num_envs == 1"):
            ddpg.make_update_loop(1, _small_cfg(nstep=3, num_envs=16))

    def test_td3_nstep_learns_point_mass(self):
        env = make_point_mass()
        cfg = ddpg.td3_config(
            num_envs=1, steps_per_iter=16, updates_per_iter=8, nstep=3,
            buffer_capacity=32768, batch_size=64, hidden=(32, 32),
            actor_lr=1e-3, critic_lr=1e-3, warmup_steps=256,
            exploration_noise=0.2,
        )
        state, metrics = ddpg.train(env, cfg, num_iterations=250, seed=3)
        assert np.isfinite(float(metrics["critic_loss"]))
        ret = _greedy_eval(env, cfg, state)
        assert ret > -1.0, ret
